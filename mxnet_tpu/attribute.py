"""Symbol attribute scoping (reference: python/mxnet/attribute.py
AttrScope) — `with AttrScope(ctx_group='dev1'):` attaches attributes
(e.g. the model-parallel __ctx_group__) to symbols created inside."""

import threading

__all__ = ["AttrScope"]


class AttrScope(object):
    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs

    def get(self, attr):
        """Merge user-supplied attrs with the scope's attrs."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current():
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value
