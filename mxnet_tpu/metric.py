"""Evaluation metrics.

Reference: python/mxnet/metric.py:68-1666 — EvalMetric hierarchy with
registry, CompositeEvalMetric, and ~20 concrete metrics.

TPU note: metric state (sum_metric/num_inst) is host-side python floats;
predictions are pulled to host once per update. Heavy per-batch math
(argmax/topk) runs on device via jnp before the single transfer.
"""

import math

import numpy as _np
import jax.numpy as jnp

from . import ndarray
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass, *aliases):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    for a in aliases:
        _METRIC_REGISTRY[a.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    """mx.metric.create (metric.py:46)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        if metric.lower() not in _METRIC_REGISTRY:
            raise ValueError("Metric must be either callable or in registry: %s"
                             % metric)
        return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)
    raise TypeError("metric should be callable, str, EvalMetric or list")


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """metric.py:36 helper."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))
    if wrap:
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


class EvalMetric(object):
    """Base metric (metric.py:68)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.global_sum_metric / self.global_num_inst)
        return self.get()

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        if self._has_global_stats:
            name, value = self.get_global()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            return list(zip(name, value))
        return self.get_name_value()

    def _inc(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


class CompositeEvalMetric(EvalMetric):
    """Manages multiple metrics (metric.py:315)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}".format(
                index, len(self.metrics)))

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {name: label for name, label in labels.items()
                      if name in self.label_names}
        if self.output_names is not None:
            preds = {name: pred for name, pred in preds.items()
                     if name in self.output_names}
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def reset_local(self):
        try:
            for metric in self.metrics:
                metric.reset_local()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, _np.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, _np.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_config(self):
        config = super().get_config()
        config.update({"metrics": [i.get_config() for i in self.metrics]})
        return config


@register
class Accuracy(EvalMetric):
    """Classification accuracy (metric.py:393)."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            pred_np = _as_np(pred_label)
            if pred_np.ndim > _as_np(label).ndim:
                pred_np = _np.argmax(pred_np, axis=self.axis)
            pred_np = pred_np.astype("int32")
            label_np = _as_np(label).astype("int32")
            label_np, pred_np = check_label_shapes(label_np, pred_np)
            correct = (pred_np.flat == label_np.flat).sum()
            self._inc(float(correct), len(pred_np.flat))


@register
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (metric.py:480)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, "Predictions should be no more than 2 dims"
            pred_np = _np.argsort(_as_np(pred_label).astype("float32"), axis=-1)
            label_np = _as_np(label).astype("int32")
            num_samples = pred_np.shape[0]
            num_dims = len(pred_np.shape)
            if num_dims == 1:
                self._inc(float((pred_np.flat == label_np.flat).sum()),
                          num_samples)
            elif num_dims == 2:
                num_classes = pred_np.shape[1]
                top_k = min(num_classes, self.top_k)
                correct = 0.0
                for j in range(top_k):
                    correct += (pred_np[:, num_classes - 1 - j].flat ==
                                label_np.flat).sum()
                self._inc(float(correct), num_samples)


class _BinaryClassificationMetrics(object):
    """Running TP/FP/TN/FN used by F1 and MCC (metric.py:573)."""

    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred_np = _as_np(pred)
        label_np = _as_np(label).astype("int32")
        pred_label = _np.argmax(pred_np, axis=1) if pred_np.ndim > 1 else \
            (pred_np > 0.5).astype("int32")
        check_label_shapes(label_np, pred_label)
        if len(_np.unique(label_np)) > 2:
            raise ValueError("%s currently only supports binary classification."
                             % self.__class__.__name__)
        pred_true = (pred_label == 1)
        pred_false = 1 - pred_true
        label_true = (label_np.flat == 1)
        label_false = 1 - label_true
        true_pos = (pred_true.flat * label_true).sum()
        false_pos = (pred_true.flat * label_false).sum()
        false_neg = (pred_false.flat * label_true).sum()
        true_neg = (pred_false.flat * label_false).sum()
        self.true_positives += true_pos
        self.false_positives += false_pos
        self.false_negatives += false_neg
        self.true_negatives += true_neg

    @property
    def precision(self):
        if self.true_positives + self.false_positives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_positives)
        return 0.

    @property
    def recall(self):
        if self.true_positives + self.false_negatives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_negatives)
        return 0.

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (
                self.precision + self.recall)
        return 0.

    @property
    def matthewscc(self):
        if not self.total_examples:
            return 0.
        true_pos = float(self.true_positives)
        false_pos = float(self.false_positives)
        false_neg = float(self.false_negatives)
        true_neg = float(self.true_negatives)
        terms = [(true_pos + false_pos), (true_pos + false_neg),
                 (true_neg + false_pos), (true_neg + false_neg)]
        denom = 1.
        for t in filter(lambda t: t != 0., terms):
            denom *= t
        return ((true_pos * true_neg) - (false_pos * false_neg)) / \
            math.sqrt(denom)

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives +
                self.true_negatives + self.true_positives)

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    """Binary F1 (metric.py:683)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.global_sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.global_num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.global_sum_metric = self.sum_metric
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self.global_sum_metric = 0.
        self.global_num_inst = 0.
        self.metrics.reset_stats()

    reset_local = reset


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (metric.py:776)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        if self._average == "macro":
            self.sum_metric += self._metrics.matthewscc
            self.global_sum_metric += self._metrics.matthewscc
            self.num_inst += 1
            self.global_num_inst += 1
            self._metrics.reset_stats()
        else:
            self.sum_metric = self._metrics.matthewscc * self._metrics.total_examples
            self.global_sum_metric = self.sum_metric
            self.num_inst = self._metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self.global_sum_metric = 0.
        self.global_num_inst = 0.
        self._metrics.reset_stats()

    reset_local = reset


@register
class Perplexity(EvalMetric):
    """Perplexity (metric.py:880)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            label_np = _as_np(label).astype("int32").reshape(-1)
            pred_np = _as_np(pred).astype("float64")
            pred_np = pred_np.reshape(-1, pred_np.shape[-1])
            probs = pred_np[_np.arange(label_np.shape[0]), label_np]
            if self.ignore_label is not None:
                ignore = (label_np == self.ignore_label).astype(pred_np.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= _np.sum(_np.log(_np.maximum(1e-10, probs)))
            num += label_np.shape[0]
        self._inc(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.global_sum_metric / self.global_num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (metric.py:971)."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._inc(float(_np.abs(label_np - pred_np).mean()), 1)


@register
class MSE(EvalMetric):
    """Mean squared error (metric.py:1021)."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._inc(float(((label_np - pred_np) ** 2.0).mean()), 1)


@register
class RMSE(EvalMetric):
    """Root mean squared error (metric.py:1071)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._inc(float(_np.sqrt(((label_np - pred_np) ** 2.0).mean())), 1)


@register
class CrossEntropy(EvalMetric):
    """Cross-entropy of predicted prob at the label (metric.py:1122)."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            label_np = label_np.ravel()
            assert label_np.shape[0] == pred_np.shape[0]
            prob = pred_np[_np.arange(label_np.shape[0]), _np.int64(label_np)]
            cross_entropy = (-_np.log(prob + self.eps)).sum()
            self._inc(float(cross_entropy), label_np.shape[0])


@register
class NegativeLogLikelihood(EvalMetric):
    """NLL (metric.py:1180)."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            label_np = label_np.ravel()
            num_examples = pred_np.shape[0]
            assert label_np.shape[0] == num_examples, \
                (label_np.shape[0], num_examples)
            prob = pred_np[_np.arange(num_examples, dtype=_np.int64),
                           _np.int64(label_np)]
            nll = (-_np.log(prob + self.eps)).sum()
            self._inc(float(nll), num_examples)


@register
class PearsonCorrelation(EvalMetric):
    """Pearson correlation (metric.py:1238)."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, False, True)
            label_np = _as_np(label).ravel().astype(_np.float64)
            pred_np = _as_np(pred).ravel().astype(_np.float64)
            self._inc(float(_np.corrcoef(pred_np, label_np)[0, 1]), 1)


@register
class Loss(EvalMetric):
    """Mean of a loss output (metric.py:1296)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = float(_as_np(pred).sum())
            self._inc(loss, int(_np.prod(pred.shape)))


@register
class Torch(Loss):
    """Legacy alias (metric.py:1330)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Legacy alias (metric.py:1338)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wraps a feval function (metric.py:1346)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label_np = _as_np(label)
            pred_np = _as_np(pred)
            reval = self._feval(label_np, pred_np)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._inc(sum_metric, num_inst)
            else:
                self._inc(reval, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


# `acc`, `ce`, `nll_loss` aliases (metric registry names in the reference)
register(Accuracy, "acc")
register(CrossEntropy, "ce")
register(NegativeLogLikelihood, "nll_loss")
register(TopKAccuracy, "top_k_accuracy", "top_k_acc")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """mx.metric.np — make a CustomMetric from a numpy feval (metric.py:1422)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
