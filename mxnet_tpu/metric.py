"""Evaluation metrics with device-side batch statistics.

API parity target: python/mxnet/metric.py (EvalMetric hierarchy, registry,
CompositeEvalMetric, the ~20 concrete metrics and the `mx.metric.np`
factory). The implementation is TPU-native rather than a transcription:
every concrete metric declares a pure *stat kernel* — a jnp function
mapping one (label, pred) batch to a short vector of sufficient
statistics. Kernels are jit-compiled once per input shape and run on
device, so the host sees a single tiny transfer per update instead of
pulling whole prediction arrays through `asnumpy` the way the reference
metrics do. Host-side state is just the running reduction of those
statistics (a few floats per metric).
"""

import math

import numpy as _np
import jax
import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass, *aliases):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    for a in aliases:
        _METRIC_REGISTRY[a.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    """mx.metric.create — resolve str / callable / list / instance."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for child in metric:
            out.add(create(child, *args, **kwargs))
        return out
    if isinstance(metric, str):
        klass = _METRIC_REGISTRY.get(metric.lower())
        if klass is None:
            raise ValueError(
                "metric %r is not registered and not callable" % metric)
        return klass(*args, **kwargs)
    raise TypeError("metric should be callable, str, EvalMetric or list")


def _on_device(x):
    """Move one update() argument onto the device untouched."""
    if isinstance(x, NDArray):
        return x._data
    return jnp.asarray(x)


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Raise when label/pred lists (or shapes, with shape=True) disagree."""
    a = labels.shape if shape else len(labels)
    b = preds.shape if shape else len(preds)
    if a != b:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(a, b))
    if wrap:
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
    return labels, preds


class EvalMetric(object):
    """Base metric: running (sum_metric, num_inst) with local+global views."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.global_sum_metric / self.global_num_inst)
        return self.get()

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        if self._has_global_stats:
            name, value = self.get_global()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            return list(zip(name, value))
        return self.get_name_value()

    def _inc(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


class CompositeEvalMetric(EvalMetric):
    """Fans update() out to children; get() concatenates their results."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            raise ValueError("Metric index {} is out of range 0 and {}"
                             .format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {k: v for k, v in labels.items() if k in self.label_names}
        if self.output_names is not None:
            preds = {k: v for k, v in preds.items() if k in self.output_names}
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def reset_local(self):
        for metric in getattr(self, "metrics", []):
            metric.reset_local()

    def _gather(self, getter):
        names, values = [], []
        for metric in self.metrics:
            name, value = getter(metric)
            names.extend([name] if isinstance(name, str) else name)
            values.extend(
                [value] if isinstance(value, (float, int, _np.generic))
                else value)
        return names, values

    def get(self):
        return self._gather(lambda m: m.get())

    def get_global(self):
        return self._gather(lambda m: m.get_global())

    def get_config(self):
        config = super().get_config()
        config.update({"metrics": [m.get_config() for m in self.metrics]})
        return config


class _KernelMetric(EvalMetric):
    """A metric driven by a jitted device-side stat kernel.

    Subclasses implement `batch_stats(label, pred) -> tuple of scalars`
    as pure jnp; `update` runs it on device (compiled once per shape) and
    folds the fetched scalars into host accumulators via `accumulate`.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._jitted = jax.jit(self.batch_stats)

    def batch_stats(self, label, pred):
        raise NotImplementedError()

    def check_shapes(self, label, pred):
        """Host-side shape validation before the kernel; override to add."""

    def accumulate(self, stats):
        # default: stats == (metric_sum, instance_count)
        s, n = stats
        self._inc(float(s), int(n))

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.check_shapes(label, pred)
            out = self._jitted(_on_device(label), _on_device(pred))
            self.accumulate([float(v) for v in out])


@register
class Accuracy(_KernelMetric):
    """Fraction of rows whose argmax (along `axis`) equals the label."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        self.axis = axis
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def check_shapes(self, label, pred):
        pred_shape = tuple(pred.shape)
        if len(pred_shape) > len(tuple(label.shape)):
            axis = self.axis % len(pred_shape)
            pred_shape = pred_shape[:axis] + pred_shape[axis + 1:]
        if int(_np.prod(pred_shape)) != int(_np.prod(label.shape)):
            raise ValueError(
                "Shape of labels {} does not match shape of predictions {}"
                .format(tuple(label.shape), tuple(pred.shape)))

    def batch_stats(self, label, pred):
        if pred.ndim > label.ndim:
            pred = jnp.argmax(pred, axis=self.axis)
        label = label.reshape(-1).astype(jnp.int32)
        pred = pred.reshape(-1).astype(jnp.int32)
        return jnp.sum(pred == label), label.size


@register
class TopKAccuracy(_KernelMetric):
    """Label appears among the k largest scores — lax.top_k on device."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        self.top_k = top_k
        assert top_k > 1, "Please use Accuracy if top_k is no more than 1"
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.name += "_%d" % top_k

    def batch_stats(self, label, pred):
        if pred.ndim == 1:
            # reference parity: a 1-D pred is ranked (argsort) and the
            # resulting ordering indices are compared against the label
            ranked = jnp.argsort(pred).astype(jnp.int32)
            hit = ranked == label.astype(jnp.int32)
            return jnp.sum(hit), label.size
        assert pred.ndim == 2, "Predictions should be no more than 2 dims"
        k = min(self.top_k, pred.shape[1])
        _, idx = jax.lax.top_k(pred, k)           # (n, k) indices, MXU-free
        hit = idx == label.reshape(-1, 1).astype(idx.dtype)
        return jnp.sum(hit), pred.shape[0]


class _ConfusionMetric(_KernelMetric):
    """Shared machinery for binary-confusion metrics (F1, MCC).

    The kernel reduces a batch to the 4 confusion counts on device; the
    derived score is computed on host from the running counts.  `average`
    follows the reference: 'macro' re-derives the score per batch and
    averages; anything else ('micro') scores the pooled counts.
    """

    def __init__(self, name, average, output_names=None, label_names=None):
        self.average = average
        self._counts = _np.zeros(4)   # tp, fp, fn, tn
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def batch_stats(self, label, pred):
        if pred.ndim > 1:
            hard = jnp.argmax(pred, axis=1)
        else:
            hard = (pred > 0.5).astype(jnp.int32)
        hard = hard.reshape(-1).astype(jnp.bool_)
        truth = label.reshape(-1).astype(jnp.bool_)
        tp = jnp.sum(hard & truth)
        fp = jnp.sum(hard & ~truth)
        fn = jnp.sum(~hard & truth)
        tn = jnp.sum(~hard & ~truth)
        return tp, fp, fn, tn

    def score(self, tp, fp, fn, tn):
        raise NotImplementedError()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            lbl = _to_numpy(label)
            if len(_np.unique(lbl.astype("int32"))) > 2:
                raise ValueError(
                    "%s currently only supports binary classification."
                    % self.__class__.__name__)
            stats = self._jitted(_on_device(lbl), _on_device(pred))
            self._counts += _np.array([float(v) for v in stats])
        if self.average == "macro":
            self.sum_metric += self.score(*self._counts)
            self.global_sum_metric += self.score(*self._counts)
            self.num_inst += 1
            self.global_num_inst += 1
            self._counts[:] = 0
        else:
            total = self._counts.sum()
            self.sum_metric = self.score(*self._counts) * total
            self.global_sum_metric = self.sum_metric
            self.num_inst = total
            self.global_num_inst = total

    def reset(self):
        super().reset()
        if hasattr(self, "_counts"):
            self._counts[:] = 0

    def reset_local(self):
        self.reset()


@register
class F1(_ConfusionMetric):
    """Harmonic mean of precision and recall over binary predictions."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, average, output_names, label_names)

    def score(self, tp, fp, fn, tn):
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        if precision + recall == 0:
            return 0.0
        return 2 * precision * recall / (precision + recall)


@register
class MCC(_ConfusionMetric):
    """Matthews correlation coefficient from the confusion counts."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, average, output_names, label_names)

    def score(self, tp, fp, fn, tn):
        if tp + fp + fn + tn == 0:
            return 0.0
        denom = 1.0
        for term in (tp + fp, tp + fn, tn + fp, tn + fn):
            if term != 0.0:
                denom *= term
        return (tp * tn - fp * fn) / math.sqrt(denom)


@register
class Perplexity(_KernelMetric):
    """exp(mean NLL of the prob the model assigns to the label)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        self.ignore_label = ignore_label
        self.axis = axis
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)

    def batch_stats(self, label, pred):
        flat = label.reshape(-1).astype(jnp.int32)
        probs = jnp.take_along_axis(
            pred.reshape(-1, pred.shape[-1]),
            flat[:, None], axis=-1)[:, 0].astype(jnp.float32)
        count = flat.size
        if self.ignore_label is not None:
            keep = flat != self.ignore_label
            probs = jnp.where(keep, probs, 1.0)
            count = jnp.sum(keep)
        nll = -jnp.sum(jnp.log(jnp.maximum(probs, 1e-10)))
        return nll, count

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name,
                math.exp(self.global_sum_metric / self.global_num_inst))


class _RegressionMetric(_KernelMetric):
    """Per-batch mean of an elementwise error; num_inst counts batches."""

    def __init__(self, name, output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def elem_error(self, diff):
        raise NotImplementedError()

    def finalize(self, mean_err):
        return mean_err

    def batch_stats(self, label, pred):
        label = label.reshape(label.shape[0], -1).astype(jnp.float32)
        pred = pred.reshape(pred.shape[0], -1).astype(jnp.float32)
        return (jnp.mean(self.elem_error(label - pred)), 1)

    def accumulate(self, stats):
        self._inc(self.finalize(stats[0]), 1)


@register
class MAE(_RegressionMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def elem_error(self, diff):
        return jnp.abs(diff)


@register
class MSE(_RegressionMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def elem_error(self, diff):
        return diff * diff


@register
class RMSE(_RegressionMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def elem_error(self, diff):
        return diff * diff

    def finalize(self, mean_err):
        return math.sqrt(mean_err)


class _LabelProbMetric(_KernelMetric):
    """Sum of -log(prob at the true label) over rows."""

    def __init__(self, eps, name, output_names=None, label_names=None):
        self.eps = eps
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def batch_stats(self, label, pred):
        flat = label.reshape(-1).astype(jnp.int32)
        probs = jnp.take_along_axis(pred, flat[:, None], axis=-1)[:, 0]
        nll = -jnp.sum(jnp.log(probs.astype(jnp.float32) + self.eps))
        return nll, flat.size


@register
class CrossEntropy(_LabelProbMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class NegativeLogLikelihood(_LabelProbMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class PearsonCorrelation(_KernelMetric):
    """Pearson r between flattened label and pred, one jnp.corrcoef call."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def batch_stats(self, label, pred):
        assert label.shape == pred.shape, (label.shape, pred.shape)
        x = pred.reshape(-1).astype(jnp.float32)
        y = label.reshape(-1).astype(jnp.float32)
        return jnp.corrcoef(x, y)[0, 1], 1

    def accumulate(self, stats):
        self._inc(float(stats[0]), 1)


@register
class Loss(_KernelMetric):
    """Running mean of a loss output (no labels involved)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self._loss_jit = jax.jit(jnp.sum)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            self._inc(float(self._loss_jit(_on_device(pred))),
                      int(_np.prod(pred.shape)))


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wraps a user feval(label_np, pred_np) -> value or (sum, count).

    User fevals are arbitrary numpy — this is the one metric family that
    legitimately runs on host.
    """

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            result = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(result, tuple):
                self._inc(*result)
            else:
                self._inc(result, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


# registry aliases matching the reference's registered names
register(Accuracy, "acc")
register(CrossEntropy, "ce")
register(NegativeLogLikelihood, "nll_loss")
register(TopKAccuracy, "top_k_accuracy", "top_k_acc")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """mx.metric.np — build a CustomMetric from a numpy feval."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation from a KxK confusion matrix (the
    multiclass generalization of MCC; reference metric.py PCC):

        pcc = (N * tr(C) - sum_k t_k p_k)
              / (sqrt(N^2 - sum t_k^2) * sqrt(N^2 - sum p_k^2))

    with t = true counts per class, p = predicted counts per class —
    the discrete Pearson correlation of the label/prediction indicator
    vectors. The confusion matrix grows lazily as new class ids appear."""

    def __init__(self, name="pcc", output_names=None, label_names=None):
        self._conf = _np.zeros((1, 1), dtype=_np.float64)    # local
        self._gconf = _np.zeros((1, 1), dtype=_np.float64)   # epoch-global
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    @staticmethod
    def _grown(conf, k):
        if k <= conf.shape[0]:
            return conf
        c = _np.zeros((k, k), _np.float64)
        c[:conf.shape[0], :conf.shape[0]] = conf
        return c

    def _grow(self, k):
        self._conf = self._grown(self._conf, k)
        self._gconf = self._grown(self._gconf, k)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            lab = _np.asarray(label.asnumpy()).reshape(-1).astype(_np.int64)
            p = _np.asarray(pred.asnumpy())
            if p.ndim > 1 and p.shape[-1] > 1:
                p = p.reshape(-1, p.shape[-1]).argmax(axis=-1)
            else:
                p = (p.reshape(-1) > 0.5)
            p = p.astype(_np.int64)
            n = min(len(lab), len(p))
            self._grow(int(max(lab.max(initial=0), p.max(initial=0))) + 1)
            _np.add.at(self._conf, (lab[:n], p[:n]), 1.0)
            _np.add.at(self._gconf, (lab[:n], p[:n]), 1.0)
            self.num_inst += n
            self.global_num_inst += n

    @staticmethod
    def _pcc_of(c):
        n = c.sum()
        if n == 0:
            return 0.0
        t = c.sum(axis=1)
        pr = c.sum(axis=0)
        cov = n * _np.trace(c) - t @ pr
        d1 = n * n - t @ t
        d2 = n * n - pr @ pr
        if d1 <= 0 or d2 <= 0:
            return 0.0
        return float(cov / math.sqrt(d1 * d2))

    @property
    def sum_metric(self):
        return self._pcc_of(self._conf) * self.num_inst

    @sum_metric.setter
    def sum_metric(self, v):
        pass            # derived from the confusion matrix

    @property
    def global_sum_metric(self):
        return self._pcc_of(self._gconf) * self.global_num_inst

    @global_sum_metric.setter
    def global_sum_metric(self, v):
        pass

    def reset(self):
        self._conf = _np.zeros((1, 1), _np.float64)
        self._gconf = _np.zeros((1, 1), _np.float64)
        self.num_inst = 0
        self.global_num_inst = 0

    def reset_local(self):
        """Clears only the per-interval stats (Speedometer auto_reset);
        the epoch-global confusion matrix survives."""
        self._conf = _np.zeros((1, 1), _np.float64)
        self.num_inst = 0
