"""In-process support for the C predict ABI (src/predict/c_predict_api.cc).

Reference: src/c_api/c_predict_api.cc:680 — the deployment path that
lets a NON-Python program run inference. TPU-native architecture: the
compute path is jax/XLA, which lives in CPython — so the C ABI embeds
the interpreter (libpython) and drives THIS module. The C side stays a
thin argument-marshalling shim; everything substantive (symbol JSON,
parameter blobs, executor bind, jit caching) reuses the framework
as-is, which keeps the ABI honest about what runs: the same compiled
XLA program a Python user would get.

The embedding contract (all called with the GIL held by the shim):
    create(symbol_json, param_bytes, dev_type, input_names, shapes)
        -> predictor id (int)
    set_input(pid, name, flat_float32_bytes, shape) -> None
    forward(pid) -> None
    get_output_shape(pid, index) -> tuple
    get_output(pid, index) -> contiguous float32 bytes
    reshape(pid, input_names, shapes) -> None
    free(pid) -> None
Errors raise; the shim converts them into MXGetLastError() strings.
"""

import threading

import numpy as np

_predictors = {}
_next_id = [1]
_lock = threading.Lock()


class _Predictor:
    def __init__(self, symbol_json, param_bytes, dev_type, input_shapes):
        import mxnet_tpu as mx
        sym = mx.sym.load_json(symbol_json)
        arg_params, aux_params = {}, {}
        if param_bytes:
            loaded = mx.nd.load_frombuffer(param_bytes)
            for k, v in loaded.items():
                if k.startswith("arg:"):
                    arg_params[k[4:]] = v
                elif k.startswith("aux:"):
                    aux_params[k[4:]] = v
                else:           # bare names (plain nd.save dict)
                    arg_params[k] = v
        ctx = mx.cpu() if dev_type == 1 else mx.gpu(0)
        self._mx = mx
        self._sym = sym
        self._ctx = ctx
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._bind(input_shapes)

    def _bind(self, input_shapes):
        mx = self._mx
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**input_shapes)
        arg_names = self._sym.list_arguments()
        aux_names = self._sym.list_auxiliary_states()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = mx.nd.zeros(shape, ctx=self._ctx)
            elif name in self._arg_params:
                args[name] = self._arg_params[name].as_in_context(self._ctx)
            else:
                raise ValueError(
                    "parameter %r missing from the param blob" % name)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in self._aux_params:
                aux[name] = self._aux_params[name].as_in_context(self._ctx)
            else:
                aux[name] = mx.nd.zeros(shape, ctx=self._ctx)
        self._input_shapes = dict(input_shapes)
        self._exec = self._sym.bind(self._ctx, args, aux_states=aux,
                                    grad_req="null")

    def set_input(self, name, data, shape):
        if name not in self._input_shapes:
            raise KeyError("unknown input %r (declared: %s)"
                           % (name, sorted(self._input_shapes)))
        arr = np.frombuffer(data, dtype=np.float32).reshape(shape)
        self._exec.arg_dict[name]._data = \
            self._mx.nd.array(arr, ctx=self._ctx)._data

    def forward(self):
        self._outputs = self._exec.forward(is_train=False)

    def get_output_shape(self, index):
        return tuple(self._outputs[index].shape)

    def get_output(self, index):
        out = self._outputs[index].asnumpy().astype(np.float32)
        return np.ascontiguousarray(out).tobytes()

    def reshape(self, input_shapes):
        self._bind(input_shapes)


def create(symbol_json, param_bytes, dev_type, input_names, shapes):
    input_shapes = dict(zip(list(input_names), [tuple(s) for s in shapes]))
    p = _Predictor(symbol_json, param_bytes, dev_type, input_shapes)
    with _lock:
        pid = _next_id[0]
        _next_id[0] += 1
        _predictors[pid] = p
    return pid


def set_input(pid, name, data, shape):
    _predictors[pid].set_input(name, data, tuple(shape))


def forward(pid):
    _predictors[pid].forward()


def get_output_shape(pid, index):
    return _predictors[pid].get_output_shape(index)


def get_output(pid, index):
    return _predictors[pid].get_output(index)


def reshape(pid, input_names, shapes):
    _predictors[pid].reshape(
        dict(zip(list(input_names), [tuple(s) for s in shapes])))


def free(pid):
    with _lock:
        _predictors.pop(pid, None)


# --------------------------------------------------------- NDList -------
# MXNDListCreate/Get: load an nd.save blob (e.g. a mean-image file) and
# expose (key, float32 data, shape) triples to the C side.
_ndlists = {}


def ndlist_create(blob):
    import mxnet_tpu as mx
    loaded = mx.nd.load_frombuffer(blob)
    if isinstance(loaded, dict):
        items = list(loaded.items())
    else:
        items = [(str(i), v) for i, v in enumerate(loaded)]
    entries = []
    for k, v in items:
        arr = np.ascontiguousarray(v.asnumpy().astype(np.float32))
        entries.append((k, arr.tobytes(), tuple(arr.shape)))
    with _lock:
        nid = _next_id[0]
        _next_id[0] += 1
        _ndlists[nid] = entries
    return nid, len(entries)


def ndlist_get(nid, index):
    k, data, shape = _ndlists[nid][index]
    return k, data, shape


def ndlist_free(nid):
    with _lock:
        _ndlists.pop(nid, None)
