"""Post-training INT8 quantization.

Reference: python/mxnet/contrib/quantization.py (quantize_model, 923
LoC) + src/operator/quantization/calibrate.cc (minmax and KL-entropy
threshold selection) + quantize_graph_pass.cc.

Pipeline (same stages as the reference, on the TPU-native graph):
1. calibrate: run the fp32 symbol over calibration batches collecting
   each quantizable layer's input distribution — min/max ('naive') or
   KL-optimal thresholds ('entropy', the calibrate.cc histogram
   algorithm).
2. rewrite: replace FullyConnected / Convolution nodes with
   _contrib_quantized_* nodes carrying the calibrated input range as
   attrs and referencing offline-quantized int8 weights.
3. return (qsym, qarg_params, aux_params) exactly like the reference
   quantize_model, ready for bind/Module.
"""

import logging

import numpy as np

from .. import ndarray as nd
from .. import symbol as sym_mod
from ..ops.quantization_ops import quantize_weight

__all__ = ["quantize_model", "calib_graph"]

QUANTIZABLE = {"FullyConnected": "_contrib_quantized_fully_connected",
               "Convolution": "_contrib_quantized_conv"}


def _optimal_threshold_kl(abs_hist, abs_edges, num_quantized_bins=128):
    """KL-divergence threshold search over an |x| histogram
    (calibrate.cc GetOptimalThreshold, the TensorRT algorithm): for each
    candidate clip threshold, compare the clipped distribution P with
    its int8-quantized reconstruction Q and keep the threshold with the
    smallest divergence."""
    num_bins = len(abs_hist)
    best_kl = np.inf
    best_threshold = float(abs_edges[-1])
    hist = abs_hist.astype(np.float64)
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 128)):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()      # outliers clip into the edge
        total = p.sum()
        if total == 0:
            continue
        # quantize the first i bins down to num_quantized_bins levels,
        # then expand back, spreading each level's mass uniformly over
        # its source bins that were non-empty
        factor = i / num_quantized_bins
        q = np.zeros(i)
        for j in range(num_quantized_bins):
            start = int(np.floor(j * factor))
            stop = min(max(int(np.floor((j + 1) * factor)), start + 1),
                       i)
            chunk = hist[start:stop]
            nz = int((chunk != 0).sum())
            if nz:
                q[start:stop] = np.where(chunk != 0,
                                         chunk.sum() / nz, 0.0)
        pn = p / total
        qsum = q.sum()
        if qsum == 0:
            continue
        qn = q / qsum
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(
            pn[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl = kl
            best_threshold = float(abs_edges[i])
    return best_threshold


class _StreamingHistogram(object):
    """Fixed-size |x| histogram folded into across batches.

    Memory is O(bins) regardless of how much calibration data streams
    through (the reference's calibrate.cc accumulates into a fixed-width
    histogram the same way). When a batch exceeds the current range the
    range doubles and adjacent bins fold together, so old counts stay on
    exact bin boundaries.
    """

    BINS = 2048

    def __init__(self):
        self.range = None
        self.counts = np.zeros(self.BINS, np.int64)

    def add(self, absvals):
        amax = float(absvals.max()) if absvals.size else 0.0
        if self.range is None:
            self.range = max(amax, 1e-10)
        while amax > self.range:
            folded = self.counts.reshape(-1, 2).sum(axis=1)
            self.counts = np.concatenate(
                [folded, np.zeros(self.BINS // 2, np.int64)])
            self.range *= 2
        hist, _ = np.histogram(absvals, bins=self.BINS,
                               range=(0.0, self.range))
        self.counts += hist

    def edges(self):
        return np.linspace(0.0, self.range, self.BINS + 1)


class _LayerCollector(object):
    """Accumulates per-tensor statistics across calibration batches."""

    def __init__(self, mode):
        self.mode = mode
        self.minmax = {}        # name -> [min, max]
        self.hists = {}         # name -> _StreamingHistogram (entropy mode)

    def update(self, name, arr):
        a = arr if isinstance(arr, np.ndarray) else arr.asnumpy()
        mn, mx = float(a.min()), float(a.max())
        if name in self.minmax:
            old = self.minmax[name]
            self.minmax[name] = [min(old[0], mn), max(old[1], mx)]
        else:
            self.minmax[name] = [mn, mx]
        if self.mode == "entropy":
            self.hists.setdefault(
                name, _StreamingHistogram()).add(np.abs(a.ravel()))

    def thresholds(self):
        out = {}
        for name, (mn, mx) in self.minmax.items():
            if self.mode == "entropy":
                hist = self.hists[name]
                t = _optimal_threshold_kl(hist.counts, hist.edges())
                out[name] = (-t, t)
            else:
                out[name] = (mn, mx)
        return out


def calib_graph(symbol, arg_params, aux_params, calib_data, data_names,
                collector, num_calib_examples=None, ctx=None,
                excluded_names=()):
    """Run fp32 forward over calibration batches, collecting the input
    tensor of every quantizable node (the reference collects via
    monitor callbacks on the executor)."""
    from ..context import cpu
    ctx = ctx or cpu()
    excluded_names = set(excluded_names)
    # outputs we need: each quantizable node's data input tensor
    node_index = {id(n): i for i, n in enumerate(symbol._nodes)}
    want = {}           # layer name -> (node list index, out index)
    for node in symbol._active_nodes():
        if node.op in QUANTIZABLE and node.name not in excluded_names:
            src_sym, oi = node.inputs[0]
            src = src_sym._nodes[src_sym._outputs[0][0]]
            want[node.name] = (node_index[id(src)], oi)
    tap_refs = sorted(set(want.values()))
    if not tap_refs:
        return
    tap_pos = {ref: i for i, ref in enumerate(tap_refs)}
    group = sym_mod.Group([sym_mod.Symbol(symbol._nodes, [ref])
                           for ref in tap_refs])
    shapes = {}
    first = next(iter(calib_data))
    calib_data.reset()
    for dn, arr in zip(data_names, first.data):
        shapes[dn] = arr.shape
    ex = group.simple_bind(ctx, grad_req="null", **shapes)
    wanted_args = set(group.list_arguments())
    wanted_aux = set(group.list_auxiliary_states())
    ex.copy_params_from(
        {k: v for k, v in arg_params.items() if k in wanted_args},
        {k: v for k, v in (aux_params or {}).items() if k in wanted_aux})
    seen = 0
    for batch in calib_data:
        feed = dict(zip(data_names, batch.data))
        outs = ex.forward(is_train=False, **feed)
        for layer, ref in want.items():
            collector.update(layer, outs[tap_pos[ref]])
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    calib_data.reset()


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None,
                   label_names=("softmax_label",), logger=None,
                   fold_bn=False):
    """Reference quantize_model API: returns (qsym, qarg_params,
    aux_params). fold_bn=True first folds Conv+BN pairs into the conv
    weights (contrib.fold_bn) — the reference's fuse-then-quantize
    subgraph flow — so the quantized conv absorbs the normalization
    instead of sandwiching an fp32 BN between int8 ops."""
    logger = logger or logging.getLogger(__name__)
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError("quantized_dtype %s not supported (int8 only)"
                         % quantized_dtype)
    if fold_bn:
        from .fold_bn import fold_batch_norm
        sym, arg_params, aux_params = fold_batch_norm(
            sym, arg_params, aux_params)
    excluded = set(excluded_sym_names)

    thresholds = {}
    if calib_mode != "none":
        if calib_data is None:
            raise ValueError("calib_mode=%s requires calib_data"
                             % calib_mode)
        collector = _LayerCollector(calib_mode)
        calib_graph(sym, arg_params, aux_params, calib_data,
                    list(data_names), collector, num_calib_examples,
                    ctx=ctx, excluded_names=excluded)
        thresholds = collector.thresholds()
        logger.info("calibrated %d layers (%s mode)", len(thresholds),
                    calib_mode)

    qarg_params = dict(arg_params)
    nodes = sym._nodes
    new_syms = {}   # id(old node) -> Symbol producing its replacement
    out_map = {}
    for node in sym._active_nodes():
        if node.is_var():
            continue
        new_inputs = []
        for s, oi in node.inputs:
            src = s._nodes[s._outputs[0][0]]
            rep = new_syms.get(id(src))
            if rep is not None:
                new_inputs.append(rep[oi] if
                                  len(rep._outputs) > oi else rep)
            else:
                new_inputs.append(sym_mod.Symbol(s._nodes,
                                                 [s._outputs[0]]))
        if node.op in QUANTIZABLE and node.name not in excluded and \
                (calib_mode == "none" or node.name in thresholds):
            in_names = list(node.attrs.get("__input_names__", ()))
            wname = node.name + "_weight"
            bname = node.name + "_bias"
            w = arg_params.get(wname)
            if w is None:
                new_syms[id(node)] = _recompose(node, new_inputs)
                continue
            qw, wscale = quantize_weight(w._data)
            qarg_params[wname + "_quantize"] = nd.NDArray(qw, w._ctx)
            mn, mx = thresholds.get(node.name, (0.0, 0.0))
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            attrs.update({"data_min": float(mn), "data_max": float(mx),
                          "weight_scale": float(wscale)})
            qop = QUANTIZABLE[node.op]
            qweight_var = sym_mod.var(wname + "_quantize",
                                      shape=tuple(w.shape),
                                      dtype="int8")
            ins = [new_inputs[0], qweight_var]
            names = ["data", "weight"]
            if not node.attrs.get("no_bias", False):
                # resolve the bias through the graph input, not the
                # <name>_bias convention — rewrites like fold_bn splice
                # bias vars under other names
                bidx = in_names.index("bias") if "bias" in in_names \
                    else None
                bias_sym = new_inputs[bidx] \
                    if bidx is not None and bidx < len(new_inputs) \
                    else (sym_mod.var(bname) if bname in arg_params
                          else None)
                if bias_sym is not None:
                    bnode = bias_sym._nodes[bias_sym._outputs[0][0]]
                    bias_param = arg_params.get(bnode.name) \
                        if bnode.is_var() else None
                    if bias_param is not None:
                        # quantized ops have no auto param-shape rule;
                        # pin the known bias shape for inference
                        bnode.attrs.setdefault(
                            "__shape__", tuple(bias_param.shape))
                    if bias_param is not None or not bnode.is_var():
                        ins.append(bias_sym)
                        names.append("bias")
            attrs["__input_names__"] = tuple(names)
            new_syms[id(node)] = sym_mod._compose(
                qop, ins, attrs, node.name + "_quantized")
        else:
            new_syms[id(node)] = _recompose(node, new_inputs)
        out_map[id(node)] = new_syms[id(node)]

    outs = []
    for ni, oi in sym._outputs:
        node = nodes[ni]
        rep = out_map.get(id(node))
        if rep is None:
            outs.append(sym_mod.Symbol(nodes, [(ni, oi)]))
        else:
            outs.append(rep[oi] if len(rep._outputs) > oi else rep)
    qsym = sym_mod.Group(outs) if len(outs) > 1 else outs[0]
    return qsym, qarg_params, dict(aux_params)


def _recompose(node, new_inputs):
    """Copy a node on top of (possibly rewritten) inputs."""
    attrs = dict(node.attrs)
    return sym_mod._compose(node.op, new_inputs, attrs, node.name)
