"""SVRG (stochastic variance-reduced gradient) training.

API parity target: python/mxnet/contrib/svrg_optimization/ (SVRGModule
driving an _SVRGOptimizer). Design divergence, documented: the reference
smuggles the variance-reduction term through a wrapper optimizer and
special kvstore keys; here the correction g(w) - g(w_snapshot) + mu is
applied to the gradient arrays directly inside SVRGModule.update(), so
any stock optimizer works unmodified and the update math is in one
place.
"""

from .svrg_module import SVRGModule

__all__ = ["SVRGModule"]
