"""SVRGModule — Module with stochastic variance-reduced gradients.

Reference behavior (contrib/svrg_optimization/svrg_module.py): every
`update_freq` epochs, snapshot the parameters and accumulate the full-
dataset gradient mu at the snapshot; each step then updates with
    g_i(w) - g_i(w_s) + mu
which is unbiased with variance shrinking as w approaches w_s
(Johnson & Zhang, 2013).

TPU-native mechanics: a shadow Module bound to the same symbol holds
the snapshot weights; per step it replays the batch to get g_i(w_s) as
one extra compiled forward+backward, and the correction is applied to
the primary module's gradient arrays before the optimizer runs.
"""

from ... import ndarray as nd
from ...module import Module


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 update_freq=2, **kwargs):
        import logging
        logger = logger or logging
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        if update_freq < 1:
            raise ValueError("update_freq must be at least 1")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._full_grads = None        # name -> NDArray (mu)
        self._cur_batch = None

    # ------------------------------------------------------- lifecycle --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, None,
                               grad_req)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        super().init_params(initializer, arg_params, aux_params,
                            allow_missing, force_init, allow_extra)
        self._take_snapshot()

    def _take_snapshot(self):
        args, auxs = self.get_params()
        self._mod_aux.init_params(
            initializer=None,
            arg_params={k: v.copy() for k, v in args.items()},
            aux_params={k: v.copy() for k, v in auxs.items()},
            allow_missing=False, force_init=True)

    # ----------------------------------------------------------- steps --
    def forward_backward(self, data_batch):
        self._cur_batch = data_batch
        super().forward_backward(data_batch)

    def update_full_grads(self, train_data):
        """Accumulate mu = (1/N) sum_i g_i(w_s) over the whole dataset at
        the current snapshot, and refresh the snapshot first."""
        self._take_snapshot()
        train_data.reset()
        totals = {}
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            for name, grad in zip(self._grad_names(self._mod_aux),
                                  self._grad_arrays(self._mod_aux)):
                if grad is None:
                    continue
                if name in totals:
                    totals[name] += grad
                else:
                    totals[name] = grad.copy()
            nbatch += 1
        train_data.reset()
        if nbatch:
            self._full_grads = {k: v / float(nbatch)
                                for k, v in totals.items()}

    @staticmethod
    def _grad_names(mod):
        return mod._symbol.list_arguments()

    @staticmethod
    def _grad_arrays(mod):
        return mod._exec.grad_arrays

    def update(self):
        """Apply the variance-reduction correction, then the optimizer."""
        if self._full_grads is not None and self._cur_batch is not None:
            self._mod_aux.forward_backward(self._cur_batch)
            aux_grads = dict(zip(self._grad_names(self._mod_aux),
                                 self._grad_arrays(self._mod_aux)))
            for name, grad in zip(self._grad_names(self),
                                  self._grad_arrays(self)):
                snap_g = aux_grads.get(name)
                mu = self._full_grads.get(name)
                if grad is None or snap_g is None or mu is None:
                    continue
                grad[:] = grad - snap_g + mu
        super().update()

    # -------------------------------------------------------------- fit --
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The base fit loop with the SVRG schedule: refresh the snapshot
        + full gradient every `update_freq` epochs."""
        from ... import metric as mx_metric
        from ... import initializer as init_mod
        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, mx_metric.EvalMetric):
            eval_metric = mx_metric.create(eval_metric)
        for epoch in range(begin_epoch, num_epoch):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            self._run_epoch(train_data, eval_metric, epoch, monitor,
                            batch_end_callback, sparse_row_id_fn)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if eval_data is not None:
                res = self.score(eval_data,
                                 validation_metric or eval_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()
