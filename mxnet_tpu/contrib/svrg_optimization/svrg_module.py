"""SVRGModule — Module with stochastic variance-reduced gradients.

Reference behavior (contrib/svrg_optimization/svrg_module.py): every
`update_freq` epochs, snapshot the parameters and accumulate the full-
dataset gradient mu at the snapshot; each step then updates with
    g_i(w) - g_i(w_s) + mu
which is unbiased with variance shrinking as w approaches w_s
(Johnson & Zhang, 2013).

TPU-native mechanics: a shadow Module bound to the same symbol holds
the snapshot weights; per step it replays the batch to get g_i(w_s) as
one extra compiled forward+backward, and the correction is applied to
the primary module's gradient arrays before the optimizer runs.
"""

from ... import ndarray as nd
from ...module import Module


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 update_freq=2, **kwargs):
        import logging
        logger = logger or logging
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        if update_freq < 1:
            raise ValueError("update_freq must be at least 1")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._full_grads = None        # name -> NDArray (mu)
        self._cur_batch = None

    # ------------------------------------------------------- lifecycle --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, None,
                               grad_req)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        super().init_params(initializer, arg_params, aux_params,
                            allow_missing, force_init, allow_extra)
        self._take_snapshot()

    def _take_snapshot(self):
        args, auxs = self.get_params()
        self._mod_aux.init_params(
            initializer=None,
            arg_params={k: v.copy() for k, v in args.items()},
            aux_params={k: v.copy() for k, v in auxs.items()},
            allow_missing=False, force_init=True)

    # ----------------------------------------------------------- steps --
    def forward_backward(self, data_batch):
        self._cur_batch = data_batch
        super().forward_backward(data_batch)

    def update_full_grads(self, train_data):
        """Accumulate mu = (1/N) sum_i g_i(w_s) over the whole dataset at
        the current snapshot, and refresh the snapshot first."""
        self._take_snapshot()
        train_data.reset()
        totals = {}
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            for name, grad in zip(self._grad_names(self._mod_aux),
                                  self._grad_arrays(self._mod_aux)):
                if grad is None:
                    continue
                if name in totals:
                    totals[name] += grad
                else:
                    totals[name] = grad.copy()
            nbatch += 1
        train_data.reset()
        if nbatch:
            self._full_grads = {k: v / float(nbatch)
                                for k, v in totals.items()}

    @staticmethod
    def _grad_names(mod):
        return mod._symbol.list_arguments()

    @staticmethod
    def _grad_arrays(mod):
        return mod._exec.grad_arrays

    def update(self):
        """Apply the variance-reduction correction, then the optimizer."""
        if self._full_grads is not None and self._cur_batch is not None:
            self._mod_aux.forward_backward(self._cur_batch)
            aux_grads = dict(zip(self._grad_names(self._mod_aux),
                                 self._grad_arrays(self._mod_aux)))
            for name, grad in zip(self._grad_names(self),
                                  self._grad_arrays(self)):
                snap_g = aux_grads.get(name)
                mu = self._full_grads.get(name)
                if grad is None or snap_g is None or mu is None:
                    continue
                grad[:] = grad - snap_g + mu
        super().update()

    def _prepare_epoch(self, epoch_offset, train_data):
        """SVRG schedule hook into the base fit loop: refresh the
        snapshot + full gradient every `update_freq` epochs. All other
        fit behavior (callbacks, checkpoints, monitors, eval) is the
        inherited loop."""
        if epoch_offset % self.update_freq == 0:
            self.update_full_grads(train_data)
