"""Vocabulary — token <-> index mapping built from a Counter.

API parity target: python/mxnet/contrib/text/vocab.py. Indexing layout
matches the reference: the unknown token occupies index 0, reserved
tokens follow, then counted tokens by descending frequency (ties broken
lexically).
"""

__all__ = ["Vocabulary"]


class Vocabulary(object):
    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be at least 1")
        if reserved_tokens is not None:
            seen = set(reserved_tokens)
            if len(seen) != len(reserved_tokens) or unknown_token in seen:
                raise ValueError(
                    "reserved tokens must be unique and exclude the "
                    "unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) \
            if reserved_tokens else None
        self._idx_to_token = [unknown_token] + \
            (list(reserved_tokens) if reserved_tokens else [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter(counter, most_freq_count, min_freq)

    def _index_counter(self, counter, most_freq_count, min_freq):
        ranked = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        # most_freq_count bounds the COUNTED tokens only — unknown and
        # reserved tokens are not charged against it (reference contract)
        budget = None if most_freq_count is None else most_freq_count
        for token, freq in ranked:
            if freq < min_freq or (budget is not None and budget <= 0):
                break
            if token in self._token_to_idx:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            if budget is not None:
                budget -= 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (or list of tokens) -> index (or list); unknown -> 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range" % i)
            out.append(self._idx_to_token[i])
        return out[0] if single else out
