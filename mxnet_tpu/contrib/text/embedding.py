"""Token embeddings backed by device NDArray matrices.

API parity target: python/mxnet/contrib/text/embedding.py
(TokenEmbedding with registry, GloVe/FastText file loaders,
CustomEmbedding, CompositeEmbedding, get_pretrained_file_names). The
archive auto-download machinery is replaced by explicit local file
paths (this environment is offline); file formats are identical, so
any downloaded GloVe/fastText .txt/.vec file loads unchanged.
"""

import io
import logging
import os

import numpy as np

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "list_embedding_names", "TokenEmbedding",
           "GloVe", "FastText", "CustomEmbedding", "CompositeEmbedding"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(embedding_name, **kwargs):
    klass = _REGISTRY.get(embedding_name.lower())
    if klass is None:
        raise KeyError(
            "embedding %r is not registered (have: %s)"
            % (embedding_name, sorted(_REGISTRY)))
    return klass(**kwargs)


def list_embedding_names():
    return sorted(_REGISTRY)


class TokenEmbedding(object):
    """idx <-> token <-> vector store over one (V, D) device matrix."""

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=nd.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None        # NDArray (V, D)

    # ------------------------------------------------------- properties --
    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    # ---------------------------------------------------------- loading --
    def _load_embedding_file(self, path, elem_delim=" ", encoding="utf8"):
        """Parse a GloVe/fastText-format text file: `token v0 v1 ...`."""
        tokens = []
        vectors = []
        seen = set()
        vec_len = None
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue           # fastText header "count dim"
                token, elems = parts[0], parts[1:]
                if not elems:
                    continue
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    logging.warning(
                        "skipping token %r with vector length %d != %d",
                        token, len(elems), vec_len)
                    continue
                if token in self._token_to_idx or token in seen:
                    logging.warning(
                        "skipping duplicated token %r in %s", token, path)
                    continue
                seen.add(token)
                tokens.append(token)
                vectors.append(np.asarray(elems, np.float32))
        if vec_len is None:
            raise ValueError("no vectors found in %s" % path)
        matrix = np.empty((1 + len(tokens), vec_len), np.float32)
        matrix[0] = self._init_unknown_vec(shape=(vec_len,)).asnumpy()
        for i, vec in enumerate(vectors, start=1):
            matrix[i] = vec
        for i, token in enumerate(tokens, start=1):
            self._token_to_idx[token] = i
            self._idx_to_token.append(token)
        self._idx_to_vec = nd.array(matrix)

    # ----------------------------------------------------------- lookup --
    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            if t in self._token_to_idx:
                idx.append(self._token_to_idx[t])
            elif lower_case_backup:
                idx.append(self._token_to_idx.get(t.lower(), 0))
            else:
                idx.append(0)
        vecs = self._idx_to_vec[nd.array(idx, dtype="int32")]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        if isinstance(tokens, str):
            tokens = [tokens]
        if new_vectors.ndim == 1:
            new_vectors = new_vectors.reshape((1, -1))
        for token, vec in zip(tokens, new_vectors):
            if token not in self._token_to_idx:
                raise ValueError(
                    "token %r is not indexed in this embedding" % token)
            self._idx_to_vec[self._token_to_idx[token]] = vec


@register
class GloVe(TokenEmbedding):
    """GloVe vectors loaded from a local `glove.*.txt` file."""

    def __init__(self, pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_path is None:
            raise ValueError(
                "offline environment: pass pretrained_file_path to a "
                "local glove .txt file")
        self._load_embedding_file(pretrained_file_path)


@register
class FastText(TokenEmbedding):
    """fastText vectors loaded from a local `.vec` file."""

    def __init__(self, pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_path is None:
            raise ValueError(
                "offline environment: pass pretrained_file_path to a "
                "local fastText .vec file")
        self._load_embedding_file(pretrained_file_path)


@register
class CustomEmbedding(TokenEmbedding):
    """Any `token v0 v1 ...` formatted file."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_file(pretrained_file_path, elem_delim,
                                  encoding)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings' vectors over one vocabulary."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise TypeError("vocabulary must be a Vocabulary")
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._vocabulary = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        pieces = [emb.get_vecs_by_tokens(self._idx_to_token)
                  for emb in token_embeddings]
        self._idx_to_vec = nd.concat(*pieces, dim=1) if len(pieces) > 1 \
            else pieces[0]

    @property
    def vocabulary(self):
        return self._vocabulary
