"""Tokenization helpers (contrib/text/utils.py parity)."""

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count whitespace/delimiter-separated tokens into a Counter."""
    source_str = filter(None,
                        re.split(token_delim + "|" + seq_delim, source_str))
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    if to_lower:
        counter.update(token.lower() for token in source_str)
    else:
        counter.update(source_str)
    return counter
