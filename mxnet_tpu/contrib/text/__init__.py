"""Text utilities: vocabulary + token embeddings.

API parity target: python/mxnet/contrib/text/ (vocab.Vocabulary,
embedding.TokenEmbedding/CustomEmbedding/CompositeEmbedding + registry,
utils.count_tokens_from_str). Pretrained-archive auto-download is out of
scope in this offline environment: loaders work from local files.
"""

from . import embedding
from . import utils
from . import vocab
from .vocab import Vocabulary

__all__ = ["embedding", "utils", "vocab", "Vocabulary"]
