"""TensorRT integration surface (reference: contrib/tensorrt.py).

Unsupported by design: TensorRT is an NVIDIA inference runtime; on TPU
the same role (whole-graph fusion + low-precision inference) is played
by XLA compilation and the int8 path in contrib.quantization. These
entry points exist so reference code fails with an actionable message
instead of an AttributeError (same stance as rtc.CudaModule).
"""

__all__ = ["set_use_fp16", "get_use_fp16", "init_tensorrt_params"]

_MSG = ("TensorRT is CUDA-specific and not part of the TPU build; XLA "
        "already performs whole-graph fusion, and int8 inference lives "
        "in mxnet_tpu.contrib.quantization.quantize_model")


def set_use_fp16(status):
    raise NotImplementedError(_MSG)


def get_use_fp16():
    raise NotImplementedError(_MSG)


def init_tensorrt_params(sym, arg_params, aux_params):
    raise NotImplementedError(_MSG)
