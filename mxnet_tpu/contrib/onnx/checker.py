"""Structural validator for exported models (onnx.checker stand-in).

Enforces the ONNX graph invariants that matter for interchange: SSA form
(each tensor produced once), topological ordering of node inputs, typed
graph inputs/outputs, initializer/dims consistency, and a declared opset.
Raises ValidationError with a readable message on the first violation.
"""

import numpy as np

from . import onnx_pb2 as _pb


class ValidationError(ValueError):
    pass


def _fail(msg, *args):
    raise ValidationError(msg % args)


def check_tensor(tensor):
    if not tensor.name:
        _fail("initializer with empty name")
    if tensor.data_type == _pb.TensorProto.UNDEFINED:
        _fail("initializer %s has UNDEFINED data type", tensor.name)
    count = int(np.prod(tensor.dims)) if tensor.dims else 1
    if tensor.raw_data:
        itemsize = {
            _pb.TensorProto.FLOAT: 4, _pb.TensorProto.DOUBLE: 8,
            _pb.TensorProto.FLOAT16: 2, _pb.TensorProto.BFLOAT16: 2,
            _pb.TensorProto.INT8: 1, _pb.TensorProto.UINT8: 1,
            _pb.TensorProto.INT16: 2, _pb.TensorProto.INT32: 4,
            _pb.TensorProto.INT64: 8, _pb.TensorProto.BOOL: 1,
        }.get(tensor.data_type)
        if itemsize and len(tensor.raw_data) != count * itemsize:
            _fail("initializer %s: raw_data holds %d bytes, dims %s need %d",
                  tensor.name, len(tensor.raw_data), tuple(tensor.dims),
                  count * itemsize)


def check_graph(graph):
    if not graph.name:
        _fail("graph has no name")
    known = set()
    for vi in graph.input:
        if not vi.name:
            _fail("graph input with empty name")
        if not vi.type.HasField("tensor_type"):
            _fail("graph input %s has no tensor type", vi.name)
        known.add(vi.name)
    for init in graph.initializer:
        check_tensor(init)
        known.add(init.name)

    produced = set(known)
    for node in graph.node:
        if not node.op_type:
            _fail("node %s has empty op_type", node.name)
        for name in node.input:
            if name and name not in produced:
                _fail("node %s (%s) consumes %r before any producer",
                      node.name, node.op_type, name)
        for name in node.output:
            if not name:
                _fail("node %s has an empty output name", node.name)
            if name in produced:
                # covers both double production and shadowing a graph
                # input / initializer — SSA violations either way
                _fail("tensor %r produced twice (SSA violation)", name)
            produced.add(name)
        for attr in node.attribute:
            if not attr.name:
                _fail("node %s has an unnamed attribute", node.name)
            if attr.type == _pb.AttributeProto.UNDEFINED:
                _fail("node %s attribute %s has UNDEFINED type",
                      node.name, attr.name)

    if not graph.output:
        _fail("graph has no outputs")
    for vi in graph.output:
        if vi.name not in produced:
            _fail("graph output %r is never produced", vi.name)


def check_model(model):
    """Validate a ModelProto (bytes, path, or message)."""
    if isinstance(model, (bytes, bytearray)):
        parsed = _pb.ModelProto()
        parsed.ParseFromString(bytes(model))
        model = parsed
    elif isinstance(model, str):
        parsed = _pb.ModelProto()
        with open(model, "rb") as f:
            parsed.ParseFromString(f.read())
        model = parsed
    if model.ir_version < 3:
        _fail("ir_version %d too old", model.ir_version)
    if not model.opset_import:
        _fail("model declares no opset_import")
    check_graph(model.graph)


validate_model = check_model
