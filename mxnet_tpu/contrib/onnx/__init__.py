"""ONNX interop (mx.contrib.onnx).

API parity target: python/mxnet/contrib/onnx/ — `export_model`
(mx2onnx/export_model.py), `import_model` / `get_model_metadata`
(onnx2mx/import_model.py).

This environment ships no `onnx` python package, so the IR schema is
vendored (`onnx.proto`, the public Apache-2.0 ONNX definition with
upstream field numbers) and compiled with protoc into `onnx_pb2` —
serialized models are byte-compatible with any ONNX runtime. A
structural validator (`checker.validate_model`) stands in for
onnx.checker.
"""

from .mx2onnx import export_model
from .onnx2mx import import_model, get_model_metadata
from . import checker

__all__ = ["export_model", "import_model", "get_model_metadata", "checker"]
