"""ONNX ModelProto -> Symbol importer.

API parity target: python/mxnet/contrib/onnx/onnx2mx/import_model.py and
import_onnx.py. Builds the graph by composing `sym.*` ops; initializers
become arg/aux params keyed by their ONNX tensor names.
"""

import numpy as np

from . import onnx_pb2 as _pb

def _bf16():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


_ONNX_TO_NP = {
    _pb.TensorProto.FLOAT: np.float32,
    _pb.TensorProto.DOUBLE: np.float64,
    _pb.TensorProto.FLOAT16: np.float16,
    _pb.TensorProto.BFLOAT16: _bf16(),
    _pb.TensorProto.INT8: np.int8,
    _pb.TensorProto.UINT8: np.uint8,
    _pb.TensorProto.INT16: np.int16,
    _pb.TensorProto.INT32: np.int32,
    _pb.TensorProto.INT64: np.int64,
    _pb.TensorProto.BOOL: np.bool_,
}

_ONNX2MX = {}


def onnx_op(*names):
    def wrap(fn):
        for n in names:
            _ONNX2MX[n] = fn
        return fn
    return wrap


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == _pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == _pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == _pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == _pb.AttributeProto.INTS:
            out[a.name] = tuple(int(v) for v in a.ints)
        elif a.type == _pb.AttributeProto.FLOATS:
            out[a.name] = tuple(float(v) for v in a.floats)
        elif a.type == _pb.AttributeProto.TENSOR:
            out[a.name] = _to_array(a.t)
    return out


def _to_array(tensor):
    dtype = _ONNX_TO_NP[tensor.data_type]
    shape = tuple(tensor.dims)
    if tensor.raw_data:
        arr = np.frombuffer(tensor.raw_data, dtype=dtype)
    elif tensor.float_data:
        arr = np.asarray(tensor.float_data, np.float32).astype(dtype)
    elif tensor.int64_data:
        arr = np.asarray(tensor.int64_data, np.int64).astype(dtype)
    elif tensor.int32_data:
        arr = np.asarray(tensor.int32_data, np.int32).astype(dtype)
    elif tensor.double_data:
        arr = np.asarray(tensor.double_data, np.float64).astype(dtype)
    else:
        arr = np.zeros(int(np.prod(shape)) if shape else 0, dtype)
    return np.array(arr).reshape(shape)


def _sym_pads(pads):
    """ONNX [b0..bn, e0..en] -> symmetric mx pad tuple, or raise."""
    n = len(pads) // 2
    begin, end = pads[:n], pads[n:]
    if tuple(begin) != tuple(end):
        raise NotImplementedError("asymmetric pads %s" % (pads,))
    return tuple(begin)


class _Importer(object):
    def __init__(self, graph):
        import mxnet_tpu.symbol as sym_mod
        self.S = sym_mod
        self.graph = graph
        self.tensors = {}       # onnx tensor name -> Symbol
        self.arrays = {}        # initializer name -> numpy (for Reshape &c)
        self.aux_names = set()

    def const(self, node_input):
        """The numpy value behind a static input (initializer)."""
        return self.arrays[node_input]

    def sym_of(self, name):
        if name not in self.tensors:
            self.tensors[name] = self.S.var(name)
        return self.tensors[name]

    def run(self):
        for init in self.graph.initializer:
            self.arrays[init.name] = _to_array(init)
        for node in self.graph.node:
            if node.domain == _CONTRIB_DOMAIN:
                result = _import_contrib_node(self, node)
            else:
                conv = _ONNX2MX.get(node.op_type)
                if conv is None:
                    raise NotImplementedError(
                        "ONNX op %r has no mx converter" % node.op_type)
                result = conv(self, node, _attrs(node))
            outs = list(node.output)
            if not isinstance(result, (list, tuple)):
                result = [result]
            for name, s in zip(outs, result):
                self.tensors[name] = s
        outputs = [self.tensors[o.name] for o in self.graph.output]
        out = outputs[0] if len(outputs) == 1 else self.S.Group(outputs)
        arg_names = set(out.list_arguments())
        aux_names = set(out.list_auxiliary_states())
        from mxnet_tpu import ndarray as nd
        args, auxs = {}, {}
        for name, arr in self.arrays.items():
            if name in aux_names:
                auxs[name] = nd.array(arr)
            elif name in arg_names:
                args[name] = nd.array(arr.astype(np.float32)
                                      if arr.dtype == np.float64 else arr)
        return out, args, auxs


# custom-domain nodes written by mx2onnx (detection heads whose
# data-dependent shapes have no opset-11 decomposition): the node
# op_type IS the mx op name and its attrs are the mx attrs verbatim
from .mx2onnx import CONTRIB_DOMAIN as _CONTRIB_DOMAIN


def _import_contrib_node(im, node):
    fn = getattr(im.S, node.op_type, None)
    if fn is None:
        raise NotImplementedError(
            "custom-domain op %r is not a registered mx op"
            % node.op_type)
    kwargs = {a.name: im.S._parse_attr(a.s.decode())
              for a in node.attribute}
    out = fn(*[im.sym_of(i) for i in node.input],
             name=node.name or None, **kwargs)
    if len(node.output) > 1:
        return [out[k] for k in range(len(node.output))]
    return out


# ------------------------------------------------------------ converters --
@onnx_op("Conv")
def _conv(im, node, attrs):
    kw = {"kernel": attrs["kernel_shape"],
          "num_group": attrs.get("group", 1)}
    if "strides" in attrs:
        kw["stride"] = attrs["strides"]
    if "dilations" in attrs:
        kw["dilate"] = attrs["dilations"]
    if "pads" in attrs:
        kw["pad"] = _sym_pads(attrs["pads"])
    w = im.const(node.input[1])
    kw["num_filter"] = w.shape[0]
    ins = [im.sym_of(i) for i in node.input]
    if len(ins) == 2:
        kw["no_bias"] = True
    return im.S.Convolution(*ins, name=node.name or None, **kw)


@onnx_op("ConvTranspose")
def _deconv(im, node, attrs):
    kw = {"kernel": attrs["kernel_shape"],
          "num_group": attrs.get("group", 1)}
    if "strides" in attrs:
        kw["stride"] = attrs["strides"]
    if "pads" in attrs:
        kw["pad"] = _sym_pads(attrs["pads"])
    w = im.const(node.input[1])
    kw["num_filter"] = w.shape[1] * attrs.get("group", 1)
    ins = [im.sym_of(i) for i in node.input]
    if len(ins) == 2:
        kw["no_bias"] = True
    return im.S.Deconvolution(*ins, name=node.name or None, **kw)


@onnx_op("MaxPool", "AveragePool")
def _pool(im, node, attrs):
    kw = {"kernel": attrs["kernel_shape"],
          "pool_type": "max" if node.op_type == "MaxPool" else "avg"}
    if "strides" in attrs:
        kw["stride"] = attrs["strides"]
    if "pads" in attrs:
        kw["pad"] = _sym_pads(attrs["pads"])
    return im.S.Pooling(im.sym_of(node.input[0]), name=node.name or None,
                        **kw)


@onnx_op("GlobalMaxPool", "GlobalAveragePool")
def _gpool(im, node, attrs):
    ptype = "max" if node.op_type == "GlobalMaxPool" else "avg"
    return im.S.Pooling(im.sym_of(node.input[0]), kernel=(1, 1),
                        pool_type=ptype, global_pool=True,
                        name=node.name or None)


@onnx_op("Gemm")
def _gemm(im, node, attrs):
    if attrs.get("alpha", 1.0) != 1.0 or attrs.get("transA", 0):
        raise NotImplementedError("general Gemm")
    beta = attrs.get("beta", 1.0)
    if beta == 0.0:
        inputs = list(node.input[:2])            # C disabled
    elif beta == 1.0:
        inputs = [i for i in node.input if i]
    else:
        raise NotImplementedError("Gemm with beta=%r" % (beta,))
    w_name = inputs[1]
    w = im.const(w_name)
    if not attrs.get("transB", 0):
        # FullyConnected computes x W^T; materialize the transposed weight
        # under a fresh name so other consumers of the initializer keep
        # the original layout
        w = np.ascontiguousarray(w.T)
        w_name = "%s__T_%s" % (w_name, node.name or "gemm")
        im.arrays[w_name] = w
    ins = [im.sym_of(inputs[0]), im.sym_of(w_name)] + \
        [im.sym_of(i) for i in inputs[2:]]
    return im.S.FullyConnected(ins[0], ins[1],
                               ins[2] if len(ins) > 2 else None,
                               num_hidden=w.shape[0], flatten=False,
                               no_bias=len(ins) <= 2,
                               name=node.name or None)


@onnx_op("MatMul")
def _matmul(im, node, attrs):
    # ONNX MatMul is numpy-matmul (batched over leading dims); mx `dot`
    # contracts last-of-a with first-of-b, so linalg_gemm2 is the match
    return im.S.linalg_gemm2(im.sym_of(node.input[0]),
                             im.sym_of(node.input[1]),
                             name=node.name or None)


@onnx_op("BatchNormalization")
def _bn(im, node, attrs):
    ins = [im.sym_of(i) for i in node.input]
    return im.S.BatchNorm(ins[0], gamma=ins[1], beta=ins[2],
                          moving_mean=ins[3], moving_var=ins[4],
                          eps=attrs.get("epsilon", 1e-5),
                          momentum=attrs.get("momentum", 0.9),
                          fix_gamma=False, name=node.name or None)


@onnx_op("Softmax")
def _softmax(im, node, attrs):
    # opset < 13 default axis is 1 (with flatten-to-2D semantics)
    return im.S.softmax(im.sym_of(node.input[0]),
                        axis=attrs.get("axis", 1),
                        name=node.name or None)


@onnx_op("Flatten")
def _flatten(im, node, attrs):
    if attrs.get("axis", 1) != 1:
        raise NotImplementedError("Flatten axis != 1")
    return im.S.Flatten(im.sym_of(node.input[0]), name=node.name or None)


@onnx_op("Dropout")
def _dropout(im, node, attrs):
    return im.S.Dropout(im.sym_of(node.input[0]),
                        p=attrs.get("ratio", 0.5), name=node.name or None)


@onnx_op("Concat")
def _concat(im, node, attrs):
    return im.S.Concat(*[im.sym_of(i) for i in node.input],
                       dim=attrs.get("axis", 1), name=node.name or None)


@onnx_op("Reshape")
def _reshape(im, node, attrs):
    shape = tuple(int(v) for v in im.const(node.input[1]))
    return im.S.Reshape(im.sym_of(node.input[0]), shape=shape,
                        name=node.name or None)


@onnx_op("Transpose")
def _transpose(im, node, attrs):
    kw = {}
    if "perm" in attrs:
        kw["axes"] = attrs["perm"]
    return im.S.transpose(im.sym_of(node.input[0]),
                          name=node.name or None, **kw)


@onnx_op("Clip")
def _clip(im, node, attrs):
    # absent bounds mean unbounded (opset 11 uses optional inputs, older
    # models use attributes); empty-string input slots are "not provided"
    lo = attrs.get("min")
    hi = attrs.get("max")
    if len(node.input) > 1 and node.input[1]:
        lo = float(im.const(node.input[1]))
    if len(node.input) > 2 and node.input[2]:
        hi = float(im.const(node.input[2]))
    lo = float("-inf") if lo is None else lo
    hi = float("inf") if hi is None else hi
    return im.S.clip(im.sym_of(node.input[0]), a_min=lo, a_max=hi,
                     name=node.name or None)


@onnx_op("Gather")
def _gather(im, node, attrs):
    return im.S.take(im.sym_of(node.input[0]), im.sym_of(node.input[1]),
                     axis=attrs.get("axis", 0), name=node.name or None)


@onnx_op("Cast")
def _cast(im, node, attrs):
    dtype = np.dtype(_ONNX_TO_NP[attrs["to"]]).name
    return im.S.Cast(im.sym_of(node.input[0]), dtype=dtype,
                     name=node.name or None)


@onnx_op("LeakyRelu")
def _leaky(im, node, attrs):
    return im.S.LeakyReLU(im.sym_of(node.input[0]), act_type="leaky",
                          slope=attrs.get("alpha", 0.01),
                          name=node.name or None)


@onnx_op("Elu")
def _elu(im, node, attrs):
    return im.S.LeakyReLU(im.sym_of(node.input[0]), act_type="elu",
                          slope=attrs.get("alpha", 1.0),
                          name=node.name or None)


@onnx_op("Pad")
def _pad(im, node, attrs):
    if len(node.input) > 1:
        raw = [int(v) for v in im.const(node.input[1])]
    else:
        raw = list(attrs["pads"])
    n = len(raw) // 2
    width = []
    for b, e in zip(raw[:n], raw[n:]):
        width.extend([b, e])
    value = 0.0
    if len(node.input) > 2:
        value = float(im.const(node.input[2]))
    return im.S.Pad(im.sym_of(node.input[0]),
                    mode=attrs.get("mode", "constant"),
                    pad_width=tuple(width), constant_value=value,
                    name=node.name or None)


def _unary(mx_name):
    def conv(im, node, attrs):
        return getattr(im.S, mx_name)(im.sym_of(node.input[0]),
                                      name=node.name or None)
    return conv


def _binary(mx_name):
    def conv(im, node, attrs):
        return getattr(im.S, mx_name)(im.sym_of(node.input[0]),
                                      im.sym_of(node.input[1]),
                                      name=node.name or None)
    return conv


for _o, _m in [("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
               ("Softplus", "softrelu"), ("Exp", "exp"), ("Log", "log"),
               ("Sqrt", "sqrt"), ("Abs", "abs"), ("Neg", "negative"),
               ("Identity", "identity"), ("Erf", "erf")]:
    _ONNX2MX[_o] = _unary(_m)

for _o, _m in [("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
               ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
               ("Max", "broadcast_maximum"), ("Min", "broadcast_minimum")]:
    _ONNX2MX[_o] = _binary(_m)


@onnx_op("Sum")
def _sum(im, node, attrs):
    return im.S.add_n(*[im.sym_of(i) for i in node.input],
                      name=node.name or None)


def _reduce(mx_name):
    def conv(im, node, attrs):
        kw = {"keepdims": bool(attrs.get("keepdims", 1))}
        if "axes" in attrs:
            kw["axis"] = attrs["axes"]
        return getattr(im.S, mx_name)(im.sym_of(node.input[0]),
                                      name=node.name or None, **kw)
    return conv


for _o, _m in [("ReduceMean", "mean"), ("ReduceSum", "sum"),
               ("ReduceMax", "max"), ("ReduceMin", "min"),
               ("ReduceProd", "prod")]:
    _ONNX2MX[_o] = _reduce(_m)


@onnx_op("MaxRoiPool")
def _max_roi_pool(im, node, attrs):
    # ONNX rois rows are [batch_idx, x1, y1, x2, y2] — mx ROIPooling's
    # exact layout
    return im.S.ROIPooling(im.sym_of(node.input[0]),
                           im.sym_of(node.input[1]),
                           pooled_size=attrs["pooled_shape"],
                           spatial_scale=attrs.get("spatial_scale", 1.0),
                           name=node.name or None)


@onnx_op("RoiAlign")
def _roi_align(im, node, attrs):
    if attrs.get("mode", "avg") != "avg":
        raise NotImplementedError("RoiAlign mode=max")
    if attrs.get("sampling_ratio", 0) <= 0:
        import warnings
        warnings.warn(
            "RoiAlign sampling_ratio<=0 means adaptive ceil(roi/bin) "
            "sampling in ONNX; this import uses a fixed 2 samples per "
            "bin (ops/contrib_ops.py roi_align), which can differ "
            "numerically for large ROIs", stacklevel=2)
    # rebuild mx's [R, 5] rois: batch indices back in column 0
    bi = im.S.Cast(im.S.expand_dims(im.sym_of(node.input[2]), axis=1),
                   dtype="float32")
    rois = im.S.Concat(bi, im.sym_of(node.input[1]), dim=1)
    return im.S.contrib.ROIAlign(
        im.sym_of(node.input[0]), rois,
        pooled_size=(attrs.get("output_height", 1),
                     attrs.get("output_width", 1)),
        spatial_scale=attrs.get("spatial_scale", 1.0),
        sample_ratio=attrs.get("sampling_ratio", -1),
        name=node.name or None)


@onnx_op("Slice")
def _slice(im, node, attrs):
    # opset 11: starts/ends/axes/steps arrive as initializer inputs
    if len(node.input) > 1:
        starts = [int(v) for v in im.const(node.input[1])]
        ends = [int(v) for v in im.const(node.input[2])]
        axes = [int(v) for v in im.const(node.input[3])] \
            if len(node.input) > 3 and node.input[3] \
            else list(range(len(starts)))
        steps = [int(v) for v in im.const(node.input[4])] \
            if len(node.input) > 4 and node.input[4] \
            else [1] * len(starts)
    else:                       # opset < 10 attribute form
        starts = list(attrs["starts"])
        ends = list(attrs["ends"])
        axes = list(attrs.get("axes", range(len(starts))))
        steps = [1] * len(starts)
    if any(st != 1 for st in steps):
        raise NotImplementedError("Slice with steps != 1")
    out = im.sym_of(node.input[0])
    int32_max = 2 ** 31 - 1
    for a, b, e in zip(axes, starts, ends):
        end = None if e >= int32_max else e
        out = im.S.slice_axis(out, axis=a, begin=b, end=end)
    return out


@onnx_op("Squeeze")
def _squeeze(im, node, attrs):
    kw = {}
    if "axes" in attrs:
        kw["axis"] = attrs["axes"]
    return im.S.squeeze(im.sym_of(node.input[0]),
                        name=node.name or None, **kw)


@onnx_op("Unsqueeze")
def _unsqueeze(im, node, attrs):
    # axes are positions in the OUTPUT rank; inserting them in
    # ascending order makes sequential expand_dims land each one where
    # the spec says. Negative axes would need the (unknown) input rank
    # to normalize — refuse loudly rather than transpose silently.
    if any(ax < 0 for ax in attrs["axes"]):
        raise NotImplementedError(
            "Unsqueeze with negative axes needs shape inference")
    out = im.sym_of(node.input[0])
    for ax in sorted(attrs["axes"]):
        out = im.S.expand_dims(out, axis=ax)
    return out


# ------------------------------------------------------------- public API --
def _load(model_file):
    model = _pb.ModelProto()
    if isinstance(model_file, (bytes, bytearray)):
        model.ParseFromString(bytes(model_file))
    else:
        with open(model_file, "rb") as f:
            model.ParseFromString(f.read())
    return model


def import_model(model_file):
    """mx.contrib.onnx.import_model -> (sym, arg_params, aux_params)."""
    model = _load(model_file)
    return _Importer(model.graph).run()


def get_model_metadata(model_file):
    """Input/output names and shapes recorded in the model."""
    model = _load(model_file)
    inits = {t.name for t in model.graph.initializer}

    def info(values):
        out = []
        for vi in values:
            shape = tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)
            out.append((vi.name, shape))
        return out

    return {
        "input_tensor_data": [x for x in info(model.graph.input)
                              if x[0] not in inits],
        "output_tensor_data": info(model.graph.output),
    }
