"""Symbol graph -> ONNX ModelProto exporter.

API parity target: python/mxnet/contrib/onnx/mx2onnx/export_model.py and
_op_translations.py. The walk here is over the reference-layout symbol
JSON (tojson), emitting one or more NodeProtos per mx node through the
converter registry below.
"""

import ast
import json

import numpy as np

from . import onnx_pb2 as _pb

# opset 11: the last opset where Dropout.ratio is an attribute and the
# first where Gemm's C input is optional — both match what we emit
_OPSET_VERSION = 11
_IR_VERSION = 7

_DTYPE_TO_ONNX = {
    "float32": _pb.TensorProto.FLOAT,
    "float64": _pb.TensorProto.DOUBLE,
    "float16": _pb.TensorProto.FLOAT16,
    "bfloat16": _pb.TensorProto.BFLOAT16,
    "int8": _pb.TensorProto.INT8,
    "uint8": _pb.TensorProto.UINT8,
    "int32": _pb.TensorProto.INT32,
    "int64": _pb.TensorProto.INT64,
    "bool": _pb.TensorProto.BOOL,
}

_MX2ONNX = {}


def mx_op(*names):
    def wrap(fn):
        for n in names:
            _MX2ONNX[n] = fn
        return fn
    return wrap


# ------------------------------------------------------------- helpers --
def _tuple(value, length=None):
    """Parse an mx attr that may be '(2, 2)', '2', or already a tuple."""
    if isinstance(value, str):
        value = ast.literal_eval(value)
    if not isinstance(value, (tuple, list)):
        value = (value,)
    out = tuple(int(v) for v in value)
    if length is not None and len(out) == 1:
        out = out * length
    return out


def _bool(value):
    if isinstance(value, str):
        return value.lower() in ("true", "1")
    return bool(value)


def _attr(node_proto, name, value):
    a = node_proto.attribute.add()
    a.name = name
    if isinstance(value, bool):
        a.type = _pb.AttributeProto.INT
        a.i = int(value)
    elif isinstance(value, int):
        a.type = _pb.AttributeProto.INT
        a.i = value
    elif isinstance(value, float):
        a.type = _pb.AttributeProto.FLOAT
        a.f = value
    elif isinstance(value, str):
        a.type = _pb.AttributeProto.STRING
        a.s = value.encode()
    elif isinstance(value, (tuple, list)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            a.type = _pb.AttributeProto.INTS
            a.ints.extend(int(v) for v in value)
        else:
            a.type = _pb.AttributeProto.FLOATS
            a.floats.extend(float(v) for v in value)
    else:
        raise TypeError("unsupported attribute %s=%r" % (name, value))


class GraphBuilder(object):
    """Accumulates NodeProtos/initializers while walking the mx graph."""

    def __init__(self, params, shapes=None):
        self.params = params          # name -> numpy array
        self.shapes = shapes or {}    # tensor name -> shape tuple (or None)
        self.nodes = []
        self.initializers = {}        # name -> numpy array emitted
        self._uid = 0

    def rank(self, tensor_name):
        shape = self.shapes.get(tensor_name)
        return len(shape) if shape else None

    def fresh(self, base):
        self._uid += 1
        return "%s__onnx%d" % (base, self._uid)

    def add_node(self, op_type, inputs, outputs, name=None, domain=None,
                 **attrs):
        n = _pb.NodeProto()
        n.op_type = op_type
        n.name = name or self.fresh(op_type.lower())
        if domain:
            n.domain = domain
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in attrs.items():
            _attr(n, k, v)
        self.nodes.append(n)
        return n

    def add_initializer(self, name, array):
        self.initializers[name] = np.asarray(array)
        return name

    def const_i64(self, base, values):
        """Emit an int64 constant initializer (Reshape shapes etc.)."""
        name = self.fresh(base)
        return self.add_initializer(name, np.asarray(values, np.int64))


# -------------------------------------------------------- op converters --
@mx_op("Convolution")
def _conv(gb, name, attrs, ins, outs):
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    kw = {
        "kernel_shape": kernel,
        "strides": _tuple(attrs.get("stride", (1,) * nd), nd),
        "dilations": _tuple(attrs.get("dilate", (1,) * nd), nd),
        "group": int(attrs.get("num_group", 1)),
    }
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    kw["pads"] = pad + pad
    gb.add_node("Conv", ins, outs, name=name, **kw)


@mx_op("Deconvolution")
def _deconv(gb, name, attrs, ins, outs):
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    gb.add_node("ConvTranspose", ins, outs, name=name,
                kernel_shape=kernel,
                strides=_tuple(attrs.get("stride", (1,) * nd), nd),
                dilations=_tuple(attrs.get("dilate", (1,) * nd), nd),
                group=int(attrs.get("num_group", 1)),
                pads=pad + pad)


@mx_op("Pooling")
def _pooling(gb, name, attrs, ins, outs):
    pool_type = attrs.get("pool_type", "max")
    if _bool(attrs.get("global_pool", False)):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[pool_type]
        gb.add_node(op, ins, outs, name=name)
        return
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    kw = {
        "kernel_shape": kernel,
        "strides": _tuple(attrs.get("stride", (1,) * nd), nd),
        "pads": pad + pad,
    }
    if pool_type == "avg":
        # ops/nn.py pooling divides by the count of in-bounds elements
        kw["count_include_pad"] = 0
        gb.add_node("AveragePool", ins, outs, name=name, **kw)
    elif pool_type == "max":
        gb.add_node("MaxPool", ins, outs, name=name, **kw)
    else:
        raise ValueError("Pooling type %s not exportable" % pool_type)


@mx_op("FullyConnected")
def _fc(gb, name, attrs, ins, outs):
    data = ins[0]
    if _bool(attrs.get("flatten", True)):
        flat = gb.fresh(name + "_flat")
        gb.add_node("Flatten", [data], [flat], axis=1)
        data = flat
    if _bool(attrs.get("no_bias", False)):
        num_hidden = int(attrs["num_hidden"])
        bias = gb.fresh(name + "_zero_bias")
        gb.add_initializer(bias, np.zeros(num_hidden, np.float32))
        gemm_in = [data, ins[1], bias]
    else:
        gemm_in = [data, ins[1], ins[2]]
    gb.add_node("Gemm", gemm_in, outs, name=name,
                alpha=1.0, beta=1.0, transA=0, transB=1)


@mx_op("Activation")
def _activation(gb, name, attrs, ins, outs):
    op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "softrelu": "Softplus", "softsign": "Softsign"}[attrs["act_type"]]
    gb.add_node(op, ins, outs, name=name)


@mx_op("LeakyReLU")
def _leaky(gb, name, attrs, ins, outs):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        gb.add_node("LeakyRelu", ins, outs, name=name,
                    alpha=float(attrs.get("slope", 0.25)))
    elif act == "elu":
        gb.add_node("Elu", ins, outs, name=name,
                    alpha=float(attrs.get("slope", 0.25)))
    elif act == "prelu":
        gb.add_node("PRelu", ins, outs, name=name)
    else:
        raise ValueError("LeakyReLU act_type %s not exportable" % act)


@mx_op("BatchNorm")
def _batchnorm(gb, name, attrs, ins, outs):
    if _bool(attrs.get("fix_gamma", True)) and ins[1] in gb.params:
        # frozen gamma: the executor treats gamma as 1 regardless of the
        # stored value, so export ones to preserve semantics
        gb.initializers[ins[1]] = np.ones_like(gb.params[ins[1]])
    gb.add_node("BatchNormalization", ins, outs, name=name,
                epsilon=float(attrs.get("eps", 1e-3)),
                momentum=float(attrs.get("momentum", 0.9)))


def _emit_softmax(gb, name, axis, ins, outs):
    """Opset-11 Softmax flattens all dims from `axis` before normalizing,
    so only last-axis softmax maps directly; other axes go through a
    transpose sandwich."""
    rank = gb.rank(ins[0])
    if rank is not None and axis is not None:
        axis = axis % rank
        if axis == rank - 1:
            gb.add_node("Softmax", ins, outs, name=name, axis=-1)
            return
        perm = list(range(rank))
        perm[axis], perm[-1] = perm[-1], perm[axis]
        moved = gb.fresh(name + "_pre")
        soft = gb.fresh(name + "_soft")
        gb.add_node("Transpose", ins, [moved], perm=perm)
        gb.add_node("Softmax", [moved], [soft], name=name, axis=-1)
        gb.add_node("Transpose", [soft], outs, perm=perm)
        return
    if axis in (-1, None):
        gb.add_node("Softmax", ins, outs, name=name, axis=-1)
        return
    raise NotImplementedError(
        "softmax over axis %r needs a known input rank to export with "
        "opset-11 coerce-to-2D semantics" % (axis,))


@mx_op("softmax", "SoftmaxActivation")
def _softmax(gb, name, attrs, ins, outs):
    _emit_softmax(gb, name, int(attrs.get("axis", -1)), ins, outs)


@mx_op("SoftmaxOutput")
def _softmax_output(gb, name, attrs, ins, outs):
    # label input is a training-only artifact; inference graph drops it
    _emit_softmax(gb, name, 1, ins[:1], outs)


@mx_op("Flatten")
def _flatten(gb, name, attrs, ins, outs):
    gb.add_node("Flatten", ins, outs, name=name, axis=1)


@mx_op("Dropout")
def _dropout(gb, name, attrs, ins, outs):
    gb.add_node("Dropout", ins, outs, name=name,
                ratio=float(attrs.get("p", 0.5)))


@mx_op("Concat")
def _concat(gb, name, attrs, ins, outs):
    gb.add_node("Concat", ins, outs, name=name,
                axis=int(attrs.get("dim", 1)))


@mx_op("Reshape")
def _reshape(gb, name, attrs, ins, outs):
    shape = _tuple(attrs["shape"])
    shape_name = gb.const_i64(name + "_shape", shape)
    gb.add_node("Reshape", [ins[0], shape_name], outs, name=name)


@mx_op("transpose")
def _transpose(gb, name, attrs, ins, outs):
    kw = {}
    if "axes" in attrs:
        kw["perm"] = _tuple(attrs["axes"])
    gb.add_node("Transpose", ins, outs, name=name, **kw)


@mx_op("clip")
def _clip(gb, name, attrs, ins, outs):
    lo = gb.add_initializer(gb.fresh(name + "_min"),
                            np.float32(attrs["a_min"]))
    hi = gb.add_initializer(gb.fresh(name + "_max"),
                            np.float32(attrs["a_max"]))
    gb.add_node("Clip", [ins[0], lo, hi], outs, name=name)


@mx_op("Embedding")
def _embedding(gb, name, attrs, ins, outs):
    # mx Embedding(data, weight) == Gather(weight, indices)
    idx = gb.fresh(name + "_idx")
    gb.add_node("Cast", [ins[0]], [idx], to=int(_pb.TensorProto.INT64))
    gb.add_node("Gather", [ins[1], idx], outs, name=name, axis=0)


@mx_op("Pad")
def _pad(gb, name, attrs, ins, outs):
    width = _tuple(attrs["pad_width"])
    ndim = len(width) // 2
    begins = width[0::2]
    ends = width[1::2]
    pads = gb.const_i64(name + "_pads", list(begins) + list(ends))
    mode = attrs.get("mode", "constant")
    value = gb.add_initializer(gb.fresh(name + "_value"),
                               np.float32(attrs.get("constant_value", 0.0)))
    gb.add_node("Pad", [ins[0], pads, value], outs, name=name, mode=mode)
    del ndim


def _simple(onnx_op, n_in=None):
    def conv(gb, name, attrs, ins, outs):
        gb.add_node(onnx_op, ins if n_in is None else ins[:n_in],
                    outs, name=name)
    return conv


for _mx_name, _onnx_name in [
        ("elemwise_add", "Add"), ("broadcast_add", "Add"), ("_plus", "Add"),
        ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
        ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
        ("elemwise_div", "Div"), ("broadcast_div", "Div"),
        ("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
        ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"), ("abs", "Abs"),
        ("negative", "Neg"), ("identity", "Identity"), ("erf", "Erf"),
        ("add_n", "Sum"),
        ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
        ("maximum", "Max"), ("minimum", "Min"),
]:
    _MX2ONNX[_mx_name] = _simple(_onnx_name)


def _dot_conv(default_rank):
    def conv(gb, name, attrs, ins, outs):
        inputs = list(ins)
        for slot, flag in ((0, "transpose_a"), (1, "transpose_b")):
            if not _bool(attrs.get(flag, False)):
                continue
            rank = gb.rank(inputs[slot]) or default_rank
            perm = list(range(rank))
            perm[-2], perm[-1] = perm[-1], perm[-2]
            moved = gb.fresh("%s_%s" % (name, flag))
            gb.add_node("Transpose", [inputs[slot]], [moved], perm=perm)
            inputs[slot] = moved
        gb.add_node("MatMul", inputs, outs, name=name)
    return conv


_MX2ONNX["dot"] = _dot_conv(2)
_MX2ONNX["batch_dot"] = _dot_conv(3)


def _reduce(onnx_op):
    def conv(gb, name, attrs, ins, outs):
        kw = {"keepdims": int(_bool(attrs.get("keepdims", False)))}
        if attrs.get("axis") not in (None, "None", "()"):
            axes = _tuple(attrs["axis"])
            if _bool(attrs.get("exclude", False)):
                rank = gb.rank(ins[0])
                if rank is None:
                    raise NotImplementedError(
                        "reduce with exclude=True needs a known input "
                        "rank to export the complement axis list")
                keep = {a % rank for a in axes}
                axes = tuple(a for a in range(rank) if a not in keep)
            kw["axes"] = axes
        gb.add_node(onnx_op, ins, outs, name=name, **kw)
    return conv


_MX2ONNX["mean"] = _reduce("ReduceMean")
_MX2ONNX["sum"] = _reduce("ReduceSum")
_MX2ONNX["max"] = _reduce("ReduceMax")
_MX2ONNX["min"] = _reduce("ReduceMin")
_MX2ONNX["prod"] = _reduce("ReduceProd")


# ------------------------------------------------- detection / attention --
@mx_op("ROIPooling")
def _roi_pooling(gb, name, attrs, ins, outs):
    # ONNX MaxRoiPool rois share mx's [batch_idx, x1, y1, x2, y2] rows
    gb.add_node("MaxRoiPool", ins, outs, name=name,
                pooled_shape=_tuple(attrs["pooled_size"]),
                spatial_scale=float(attrs.get("spatial_scale", 1.0)))


@mx_op("_contrib_ROIAlign")
def _roi_align(gb, name, attrs, ins, outs):
    if _bool(attrs.get("position_sensitive", False)):
        raise NotImplementedError("position-sensitive ROIAlign has no "
                                  "ONNX counterpart")
    if _bool(attrs.get("aligned", False)):
        raise NotImplementedError("aligned=True ROIAlign needs the "
                                  "opset-16 half_pixel mode; export "
                                  "targets opset 11")
    ph, pw = _tuple(attrs["pooled_size"])
    sr = int(attrs.get("sample_ratio", -1))
    # mx rois are [R, 5] (batch idx + corners); ONNX RoiAlign wants the
    # [R, 4] boxes and an int64 batch-index vector separately
    ax1 = gb.const_i64(name + "_ax", [1])
    s0 = gb.const_i64(name + "_s0", [0])
    e1 = gb.const_i64(name + "_e1", [1])
    s1 = gb.const_i64(name + "_s1", [1])
    e5 = gb.const_i64(name + "_e5", [5])
    bi_col = gb.fresh(name + "_bi_col")
    boxes = gb.fresh(name + "_boxes")
    bi_flat = gb.fresh(name + "_bi_flat")
    bi = gb.fresh(name + "_bi")
    gb.add_node("Slice", [ins[1], s0, e1, ax1], [bi_col])
    gb.add_node("Slice", [ins[1], s1, e5, ax1], [boxes])
    gb.add_node("Squeeze", [bi_col], [bi_flat], axes=(1,))
    gb.add_node("Cast", [bi_flat], [bi], to=int(_pb.TensorProto.INT64))
    # ops/contrib_ops.py roi_align defaults sample_ratio<=0 to 2 samples
    # per bin; emit that explicitly (ONNX 0 means adaptive)
    gb.add_node("RoiAlign", [ins[0], boxes, bi], outs, name=name,
                mode="avg", output_height=ph, output_width=pw,
                sampling_ratio=2 if sr <= 0 else sr,
                spatial_scale=float(attrs.get("spatial_scale", 1.0)))


# Data-dependent detection heads (greedy NMS, anchor matching) have no
# static-shape decomposition in opset 11; they export as single nodes
# in a custom domain carrying the mx attrs verbatim. Our importer (and
# any runtime registering the domain) reconstructs the op exactly; the
# reference exports none of these.
CONTRIB_DOMAIN = "org.mxnet_tpu"

_CONTRIB_PASSTHROUGH = (
    ("_contrib_box_nms", 1), ("_contrib_box_non_maximum_suppression", 1),
    ("_contrib_MultiBoxPrior", 1), ("MultiBoxPrior", 1),
    ("_contrib_MultiBoxTarget", 3), ("MultiBoxTarget", 3),
    ("_contrib_MultiBoxDetection", 1), ("MultiBoxDetection", 1),
    ("_contrib_Proposal", 1), ("_contrib_MultiProposal", 1),
    ("_contrib_box_iou", 1),
)


def _contrib_passthrough(canonical, n_out):
    def conv(gb, name, attrs, ins, outs):
        gb.add_node(canonical, ins, outs[:n_out], name=name,
                    domain=CONTRIB_DOMAIN,
                    **{k: str(v) for k, v in attrs.items()})
    conv._n_out = n_out
    return conv


for _nm, _n_out in _CONTRIB_PASSTHROUGH:
    _MX2ONNX[_nm] = _contrib_passthrough(_nm, _n_out)


def _interleaved_shapes(gb, tensor_name, attrs):
    shape = gb.shapes.get(tensor_name)
    if not shape or len(shape) != 3:
        raise NotImplementedError(
            "interleaved-matmul export needs a known (seq, batch, "
            "3*embed) input shape")
    s, b, e3 = shape
    h = int(attrs.get("heads", 1))
    e = e3 // 3
    return s, b, h, e, e // h


def _slice_head(gb, name, x5, idx, tag):
    """(s,b,h,3,hd) -> (s,b,h,hd): take q/k/v slot `idx` of axis 3."""
    s3 = gb.const_i64("%s_%s_s" % (name, tag), [idx])
    e3 = gb.const_i64("%s_%s_e" % (name, tag), [idx + 1])
    ax = gb.const_i64("%s_%s_ax" % (name, tag), [3])
    sliced = gb.fresh("%s_%s_sl" % (name, tag))
    out = gb.fresh("%s_%s" % (name, tag))
    gb.add_node("Slice", [x5, s3, e3, ax], [sliced])
    gb.add_node("Squeeze", [sliced], [out], axes=(3,))
    return out


def _to_bh(gb, name, x, s, b, h, hd, tag):
    """(s,b,h,hd) -> (b*h, s, hd)."""
    moved = gb.fresh("%s_%s_t" % (name, tag))
    gb.add_node("Transpose", [x], [moved], perm=(1, 2, 0, 3))
    shp = gb.const_i64("%s_%s_shp" % (name, tag), [b * h, s, hd])
    out = gb.fresh("%s_%s_bh" % (name, tag))
    gb.add_node("Reshape", [moved, shp], [out])
    return out


@mx_op("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_qk(gb, name, attrs, ins, outs):
    """(s, b, 3e) head-interleaved qkv -> (b*h, s, s) scaled scores,
    decomposed to standard opset-11 ops (transformer.cc semantics,
    ops/contrib_ops.py numerics)."""
    s, b, h, e, hd = _interleaved_shapes(gb, ins[0], attrs)
    shp5 = gb.const_i64(name + "_shp5", [s, b, h, 3, hd])
    x5 = gb.fresh(name + "_x5")
    gb.add_node("Reshape", [ins[0], shp5], [x5])
    q = _to_bh(gb, name, _slice_head(gb, name, x5, 0, "q"), s, b, h, hd,
               "q")
    k = _to_bh(gb, name, _slice_head(gb, name, x5, 1, "k"), s, b, h, hd,
               "k")
    kt = gb.fresh(name + "_kt")
    gb.add_node("Transpose", [k], [kt], perm=(0, 2, 1))
    raw = gb.fresh(name + "_raw")
    gb.add_node("MatMul", [q, kt], [raw])
    scale = gb.add_initializer(gb.fresh(name + "_scale"),
                               np.float32(1.0 / np.sqrt(hd)))
    gb.add_node("Mul", [raw, scale], outs, name=name)


@mx_op("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_valatt(gb, name, attrs, ins, outs):
    """(qkv, attention) -> (s, b, e) context, standard-op decomposition."""
    s, b, h, e, hd = _interleaved_shapes(gb, ins[0], attrs)
    shp5 = gb.const_i64(name + "_shp5", [s, b, h, 3, hd])
    x5 = gb.fresh(name + "_x5")
    gb.add_node("Reshape", [ins[0], shp5], [x5])
    v = _to_bh(gb, name, _slice_head(gb, name, x5, 2, "v"), s, b, h, hd,
               "v")
    ctx = gb.fresh(name + "_ctx")
    gb.add_node("MatMul", [ins[1], v], [ctx])
    shp4 = gb.const_i64(name + "_shp4", [b, h, s, hd])
    ctx4 = gb.fresh(name + "_ctx4")
    gb.add_node("Reshape", [ctx, shp4], [ctx4])
    moved = gb.fresh(name + "_moved")
    gb.add_node("Transpose", [ctx4], [moved], perm=(2, 0, 1, 3))
    shp3 = gb.const_i64(name + "_shp3", [s, b, e])
    gb.add_node("Reshape", [moved, shp3], outs, name=name)


# ------------------------------------------------------------ model walk --
def _np_param(value):
    if isinstance(value, np.ndarray):
        return value
    return value.asnumpy()          # NDArray


def _tensor_proto(name, array):
    t = _pb.TensorProto()
    t.name = name
    array = np.ascontiguousarray(array)
    t.dims.extend(array.shape)
    t.data_type = _DTYPE_TO_ONNX[array.dtype.name]
    t.raw_data = array.tobytes()
    return t


def _value_info(name, dtype, shape):
    vi = _pb.ValueInfoProto()
    vi.name = name
    vi.type.tensor_type.elem_type = _DTYPE_TO_ONNX[np.dtype(dtype).name]
    for d in shape:
        dim = vi.type.tensor_type.shape.dim.add()
        dim.dim_value = int(d)
    return vi


def create_model(sym, params, input_shapes, input_dtype=np.float32,
                 graph_name="mxnet_tpu_model"):
    """Build a ModelProto from (Symbol, params, {input: shape})."""
    graph = json.loads(sym.tojson())
    nodes = graph["nodes"]
    params = {k.split(":", 1)[-1]: _np_param(v) for k, v in params.items()}

    # per-tensor shapes (for rank-dependent conversions: reduce exclude,
    # softmax axis semantics, dot transposes)
    shapes = {name: tuple(shape) for name, shape in input_shapes.items()}
    shapes.update({name: tuple(arr.shape) for name, arr in params.items()})
    try:
        internals = sym.get_internals()
        _, internal_shapes, _ = internals.infer_shape_partial(**input_shapes)
        for out_nm, shp in zip(internals.list_outputs(), internal_shapes):
            if shp:
                shapes[out_nm] = tuple(shp)
                for suffix in ("_output", "_output0"):
                    if out_nm.endswith(suffix):
                        shapes[out_nm[:-len(suffix)]] = tuple(shp)
    except Exception:
        pass

    gb = GraphBuilder(params, shapes)
    out_name = {}           # (node_idx, out_idx) -> onnx tensor name
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            out_name[(i, 0)] = node["name"]
        else:
            out_name[(i, 0)] = node["name"]
            for extra in range(1, 4):
                out_name[(i, extra)] = "%s_out%d" % (node["name"], extra)

    for i, node in enumerate(nodes):
        op = node["op"]
        if op == "null":
            continue
        conv = _MX2ONNX.get(op)
        if conv is None:
            raise NotImplementedError(
                "mx op %r has no ONNX converter" % op)
        ins = [out_name[(ni, oi)] for ni, oi, _ in node["inputs"]]
        n_out = getattr(conv, "_n_out", 1)
        conv(gb, node["name"], node.get("attrs", {}), ins,
             [out_name[(i, k)] for k in range(n_out)])

    model = _pb.ModelProto()
    model.ir_version = _IR_VERSION
    model.producer_name = "mxnet_tpu"
    model.producer_version = "0.1.0"
    opset = model.opset_import.add()
    opset.version = _OPSET_VERSION
    if any(n.domain == CONTRIB_DOMAIN for n in gb.nodes):
        custom = model.opset_import.add()
        custom.domain = CONTRIB_DOMAIN
        custom.version = 1
    g = model.graph
    g.name = graph_name
    g.node.extend(gb.nodes)

    # data inputs = graph vars that are not params
    referenced = set()
    for n in gb.nodes:
        referenced.update(n.input)
    for name, shape in input_shapes.items():
        g.input.append(_value_info(name, input_dtype, shape))
    for name, arr in params.items():
        if name in referenced and name not in gb.initializers:
            gb.initializers[name] = arr
    for name, arr in gb.initializers.items():
        g.initializer.append(_tensor_proto(name, arr))
        g.input.append(_value_info(name, arr.dtype, arr.shape))

    # outputs: infer shapes when possible
    try:
        _, out_shapes, _ = sym.infer_shape(**input_shapes)
    except Exception:
        out_shapes = [()] * len(sym.list_outputs())
    heads = [gb_head for gb_head in graph["heads"]]
    for (ni, oi, _), shape in zip(heads, out_shapes):
        g.output.append(_value_info(out_name[(ni, oi)], input_dtype,
                                    shape or ()))
    return model


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False,
                 input_names=None):
    """mx.contrib.onnx.export_model — serialize to onnx_file_path.

    `sym` may be a Symbol or a path to a saved symbol JSON; `params` a
    dict (optionally with arg:/aux: prefixes) or a path to .params.
    `input_shape` is a list of shapes matching the graph's data inputs.
    """
    from ... import ndarray as nd
    from ... import symbol as sym_mod
    if isinstance(sym, str):
        with open(sym) as f:
            sym = sym_mod.load_json(f.read())
    if isinstance(params, str):
        params = nd.load(params)
    if isinstance(input_shape, dict):
        input_shapes = dict(input_shape)
    else:
        if not isinstance(input_shape, (list, tuple)) or \
                input_shape and not isinstance(input_shape[0],
                                               (list, tuple)):
            input_shape = [input_shape]
        param_names = {k.split(":", 1)[-1] for k in params}
        data_names = input_names or \
            [n for n in sym.list_arguments()
             if n not in param_names and not n.endswith("_label")]
        input_shapes = dict(zip(data_names, input_shape))
    model = create_model(sym, params, input_shapes, input_type)
    if verbose:
        print("exporting %d nodes -> %s" % (len(model.graph.node),
                                            onnx_file_path))
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
