"""TensorBoard logging callback.

API parity target: python/mxnet/contrib/tensorboard.py
(LogMetricsCallback). The writer dependency is optional: any object
with an `add_scalar(tag, value, global_step)` method works (tensorboardX
/ torch.utils.tensorboard SummaryWriter, or the bundled _TsvWriter
fallback that appends tag\tstep\tvalue lines so runs are inspectable
without any tensorboard install).
"""

import os
import time

__all__ = ["LogMetricsCallback"]


class _TsvWriter(object):
    """Dependency-free fallback writer: one .tsv per run directory."""

    def __init__(self, logging_dir):
        os.makedirs(logging_dir, exist_ok=True)
        self._path = os.path.join(logging_dir,
                                  "scalars_%d.tsv" % int(time.time()))

    def add_scalar(self, tag, value, global_step=None):
        with open(self._path, "a") as f:
            f.write("%s\t%s\t%r\n" % (tag, global_step, value))

    def flush(self):
        pass


def _make_writer(logging_dir):
    for mod, attr in (("torch.utils.tensorboard", "SummaryWriter"),
                      ("tensorboardX", "SummaryWriter")):
        try:
            module = __import__(mod, fromlist=[attr])
            return getattr(module, attr)(logging_dir)
        except Exception:
            continue
    return _TsvWriter(logging_dir)


class LogMetricsCallback(object):
    """Batch-end callback streaming eval metrics to TensorBoard."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _make_writer(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
