"""Dynamic loss scaling (reference:
python/mxnet/contrib/amp/loss_scaler.py).

bf16 shares fp32's exponent range, so on TPU loss scaling is a no-op in
the default bf16 policy; the scaler remains functional for users who
cast to float16 explicitly."""

import numpy as np

from ... import ndarray as nd

__all__ = ["LossScaler"]


class LossScaler(object):
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite."""
        for param in params:
            if param.grad_req != "null":
                grad = param.grad()
                if not bool(nd.isfinite(grad).min().asnumpy()):
                    return True
        return False

    def update_scale(self, skip):
        if skip:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
