"""AMP — automatic mixed precision (reference:
python/mxnet/contrib/amp/amp.py:78-288).

TPU policy: bfloat16. The reference monkey-patches every op wrapper to
insert amp_cast pairs; on TPU the policy is simpler and more robust —
cast the model's MXU-bound parameters/compute to bf16, keep the
fp32-list layers (norms, softmax heads) in fp32, and let XLA fuse the
casts away. The MXU accumulates bf16 matmuls in fp32 natively, which is
the whole reason the reference needed its 'widest dtype' machinery for
fp16 but bf16 does not."""

from contextlib import contextmanager

from ... import ndarray as nd
from ...base import MXNetError
from .lists import symbol as amp_lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "convert_symbol"]

_amp_initialized = False
_target_dtype = "bfloat16"
_loss_scaler = None


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally (tracked state consumed by init_trainer /
    scale_loss; models are converted with convert_hybrid_block)."""
    global _amp_initialized, _target_dtype, _loss_scaler
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _amp_initialized = True
    _target_dtype = target_dtype
    _loss_scaler = LossScaler() if target_dtype == "float16" else None


def init_trainer(trainer):
    """Attach the dynamic loss scaler to a Gluon Trainer (no-op for
    bf16, where scaling is unnecessary)."""
    if not _amp_initialized:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = _loss_scaler


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss (fp16 only; bf16 passes through)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    scale = 1.0 / scaler.loss_scale
    for param in trainer._params:
        if param.grad_req != "null":
            grad = param.grad()
            grad[:] = grad * scale


def _fp32_param(name):
    lname = name.lower()
    return any(k in lname for k in
               ("batchnorm", "layernorm", "groupnorm", "instancenorm",
                "gamma", "beta", "mean", "var"))


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a Gluon block for mixed precision: MXU-bound params ->
    target dtype, norm-family params stay fp32 (amp_lists.FP32_FUNCS)."""
    block.cast(target_dtype)
    for name, param in block.collect_params().items():
        if _fp32_param(name):
            param.cast("float32")
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  excluded_sym_names=None):
    """Cast a symbolic model's parameters (the graph computes in the
    dtype of its inputs; XLA folds the casts)."""
    excluded = set(excluded_sym_names or [])
    new_args = {}
    for k, v in arg_params.items():
        new_args[k] = v if (_fp32_param(k) or k in excluded) \
            else v.astype(target_dtype)
    new_aux = {k: v.astype("float32") for k, v in aux_params.items()}
    return sym, new_args, new_aux


def convert_symbol(sym, target_dtype="bfloat16", **kwargs):
    """The graph itself is dtype-polymorphic under XLA tracing; returns
    the symbol unchanged (casting happens at the parameter/input level)."""
    return sym
