"""AMP (reference: python/mxnet/contrib/amp/__init__.py)."""

from .amp import *
from .loss_scaler import LossScaler
from . import lists
