"""AMP op lists (reference: python/mxnet/contrib/amp/lists/symbol.py).

On TPU the low-precision dtype is bfloat16: matmul/conv-heavy ops run
bf16 on the MXU (fp32 accumulation is hardware-native), numerically
sensitive reductions stay fp32. bf16's fp32-equal exponent range makes
the reference's 'widest dtype' conditional list mostly unnecessary —
those ops are safe in bf16 and listed here accordingly."""

# ops that benefit from bf16 (MXU-bound)
FP16_FUNCS = [
    "Convolution", "Deconvolution", "FullyConnected", "RNN",
    "dot", "batch_dot",
]

# numerically sensitive: keep fp32
FP32_FUNCS = [
    "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "L2Normalization",
    "LRN", "SoftmaxOutput", "softmax", "log_softmax", "Softmax",
    "SoftmaxActivation", "exp", "log", "log2", "log10", "log1p", "expm1",
    "norm", "mean", "sum", "CTCLoss", "MakeLoss", "smooth_l1", "sqrt",
    "rsqrt", "square", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput",
]

# elementwise/shape ops safe in either dtype (follow their inputs)
FP16_FP32_FUNCS = [
    "Activation", "LeakyReLU", "relu", "sigmoid", "tanh", "Pooling",
    "Concat", "concat", "slice", "Reshape", "reshape", "transpose",
    "Flatten", "Dropout", "elemwise_add", "elemwise_sub", "elemwise_mul",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "add_n", "stack", "clip", "Pad", "pad", "UpSampling", "Embedding",
]

# reference keeps a 'widest type' list for ops where fp16 overflows;
# bf16 shares fp32's exponent so these are safe — kept for API parity
WIDEST_TYPE_CASTS = []

CONDITIONAL_FP32_FUNCS = []
