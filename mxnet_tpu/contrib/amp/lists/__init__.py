"""AMP op lists."""

from . import symbol
