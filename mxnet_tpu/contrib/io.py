"""Contrib IO (reference: contrib/io.py) — bridge a Gluon DataLoader
into the DataIter interface the Module API consumes."""

from ..io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a gluon.data.DataLoader yielding (data, label) pairs."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super(DataLoaderIter, self).__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        first = next(iter(loader))
        data, label = first[0], first[1]
        self.batch_size = data.shape[0]
        self.provide_data = [DataDesc(name=data_name, shape=data.shape)]
        self.provide_label = [DataDesc(name=label_name, shape=label.shape)]

    def reset(self):
        self._iter = iter(self._loader)

    def next(self):
        try:
            data, label = next(self._iter)
        except StopIteration:
            raise StopIteration
        return DataBatch([data], [label], pad=0,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
