"""Contrib IO (reference: contrib/io.py) — bridge a Gluon DataLoader
into the DataIter interface the Module API consumes."""

from ..io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a gluon.data.DataLoader yielding (data, label) pairs. The
    first batch is peeked for shapes and then SERVED (not discarded), so
    one-shot iterables keep every batch and re-iterable loaders don't
    pay a doubled first-batch cost."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super(DataLoaderIter, self).__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        self._peeked = next(self._iter)
        data, label = self._peeked[0], self._peeked[1]
        self.batch_size = data.shape[0]
        self.provide_data = [DataDesc(name=data_name, shape=data.shape)]
        self.provide_label = [DataDesc(name=label_name, shape=label.shape)]

    def reset(self):
        self._iter = iter(self._loader)
        self._peeked = None

    def next(self):
        if self._peeked is not None:
            data, label = self._peeked[0], self._peeked[1]
            self._peeked = None
        else:
            data, label = next(self._iter)
        return DataBatch([data], [label], pad=0,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
