"""Contrib namespace (reference: python/mxnet/contrib/__init__.py)."""

from . import amp
from . import onnx
from . import quantization
from . import svrg_optimization
from . import tensorboard
from . import text
