"""Contrib namespace (reference: python/mxnet/contrib/__init__.py)."""

from . import amp
from . import onnx
from . import quantization
