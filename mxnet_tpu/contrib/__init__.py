"""Contrib namespace (reference: python/mxnet/contrib/__init__.py)."""

from . import amp
from . import onnx
from . import fold_bn
from . import quantization
from . import svrg_optimization
from . import tensorboard
from . import text
from . import autograd
from . import io
from . import ndarray
from . import symbol
from . import tensorrt
