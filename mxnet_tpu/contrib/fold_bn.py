"""Inference-time Conv+BatchNorm fusion (graph + params rewrite).

Reference counterpart: the conv+BN subgraph fusion the MKLDNN and
TensorRT backends perform when quantizing / converting for deployment
(src/operator/subgraph/mkldnn/mkldnn_conv.cc fuse_bn path). There it is
a backend pass over NNVM subgraphs; here it is a pure function on the
reference-layout symbol JSON plus the parameter dict — the TPU graph
needs no backend machinery, because after folding, XLA sees a plain
conv+bias and fuses the rest.

Math (per output channel o, inference BN with global stats):
    bn(conv(x, W) + b) = conv(x, W * s) + (b - mean) * s + beta
    with s = gamma / sqrt(var + eps)
so the BN node disappears into the conv's weights and bias. Exact for
inference (is_train=False); training graphs must keep BN (batch stats).

    folded_sym, folded_args, remaining_auxs = fold_batch_norm(
        sym, args, auxs)
"""

import json

import numpy as np

__all__ = ["fold_batch_norm", "fold_block"]


def _attr_bool(attrs, name, default):
    v = attrs.get(name)
    if v is None:
        return default
    return str(v).lower() in ("1", "true")


def _np(value):
    return value if isinstance(value, np.ndarray) else value.asnumpy()


def fold_batch_norm(symbol, arg_params, aux_params):
    """Fold every foldable Conv->BatchNorm pair; returns
    (new_symbol, new_arg_params, remaining_aux_params). Foldable means:
    the BN's data input is a Convolution output consumed ONLY by that
    BN, the BN normalizes axis 1 (the conv's output-channel axis), and
    only the BN's first output is consumed. Folded BNs' moving stats
    are baked into the conv weights; unfoldable BNs (e.g. pre-
    activation BNs fed by an add) keep theirs in the returned aux
    dict."""
    from .. import ndarray as nd_mod
    from .. import symbol as sym_mod

    graph = json.loads(symbol.tojson())
    nodes = graph["nodes"]
    args = {k: _np(v) for k, v in arg_params.items()}
    auxs = {k: _np(v) for k, v in (aux_params or {}).items()}

    # consumers per (node, out_index)
    consumers = {}
    for i, node in enumerate(nodes):
        for ni, oi, _ in node["inputs"]:
            consumers.setdefault((ni, oi), []).append(i)
    for ni, oi, _ in graph["heads"]:
        consumers.setdefault((ni, oi), []).append(-1)

    drop_nodes = set()          # node indices to remove
    redirect = {}               # (bn_idx, 0) -> (conv_idx, 0)
    new_bias_nodes = {}         # conv_idx -> bias node dict (to insert)

    for bi, bn in enumerate(nodes):
        if bn["op"] != "BatchNorm":
            continue
        attrs = bn.get("attrs", {})
        if int(attrs.get("axis", 1)) != 1:
            continue
        # use_global_stats is irrelevant here: inference executors use
        # the moving stats either way, and folding is inference-only
        ci, coi, _ = bn["inputs"][0]
        conv = nodes[ci]
        if conv["op"] != "Convolution" or coi != 0:
            continue
        if consumers.get((ci, 0), []) != [bi]:
            continue            # conv output has other consumers
        if any(consumers.get((bi, k)) for k in (1, 2)):
            continue            # someone reads batch mean/var outputs
        names = [nodes[n]["name"] for n, _, _ in bn["inputs"][1:5]]
        g_name, b_name, mm_name, mv_name = names
        if mm_name not in auxs or mv_name not in auxs:
            continue
        eps = float(attrs.get("eps", 1e-3))
        fix_gamma = _attr_bool(attrs, "fix_gamma", True)
        gamma = args.get(g_name)
        beta = args.get(b_name)
        if gamma is None or beta is None:
            continue
        if fix_gamma:
            gamma = np.ones_like(gamma)
        mean = auxs[mm_name]
        var = auxs[mv_name]
        s = (gamma / np.sqrt(var + eps)).astype(np.float32)

        conv_attrs = conv.get("attrs", {})
        w_name = nodes[conv["inputs"][1][0]]["name"]
        w = args[w_name]
        args[w_name] = (w.astype(np.float32)
                        * s.reshape((-1,) + (1,) * (w.ndim - 1))
                        ).astype(w.dtype)
        no_bias = _attr_bool(conv_attrs, "no_bias", False)
        if no_bias or len(conv["inputs"]) < 3:
            old_b = np.zeros(w.shape[0], np.float32)
            bias_name = conv["name"] + "_folded_bias"
            new_bias_nodes[ci] = {"op": "null", "name": bias_name,
                                  "attrs": {}, "inputs": []}
            import ast
            in_names = conv_attrs.get("__input_names__")
            # always record the input-name tuple: downstream rewrites
            # (quantization) resolve the spliced bias through it, and
            # reference-layout JSON may not carry the attr at all
            base_names = tuple(ast.literal_eval(in_names)) if in_names \
                else ("data", "weight")
            conv_attrs["__input_names__"] = str(base_names + ("bias",))
        else:
            bias_name = nodes[conv["inputs"][2][0]]["name"]
            old_b = args[bias_name].astype(np.float32)
        args[bias_name] = ((old_b - mean) * s + beta).astype(w.dtype)
        conv_attrs["no_bias"] = "False"
        conv["attrs"] = conv_attrs

        drop_nodes.add(bi)
        for k in (1, 2, 3, 4):
            pi = bn["inputs"][k][0]
            # param nodes feeding only this BN disappear with it
            if all(c == bi for c in consumers.get((pi, 0), [])):
                drop_nodes.add(pi)
        redirect[(bi, 0)] = (ci, 0)
        for name in (g_name, b_name):
            args.pop(name, None)
        auxs.pop(mm_name, None)
        auxs.pop(mv_name, None)

    if not redirect:
        return (symbol, {k: nd_mod.array(v) for k, v in args.items()},
                {k: nd_mod.array(v) for k, v in auxs.items()})

    # rebuild the node list: drop folded nodes, splice in bias params
    new_nodes = []
    index_of = {}
    for i, node in enumerate(nodes):
        if i in drop_nodes:
            continue
        if i in new_bias_nodes:
            bias_node = new_bias_nodes[i]
            index_of[("bias", i)] = len(new_nodes)
            new_nodes.append(bias_node)
        index_of[i] = len(new_nodes)
        new_nodes.append(node)

    def map_ref(ref):
        ni, oi, vi = ref
        while (ni, oi) in redirect:
            ni, oi = redirect[(ni, oi)]
        return [index_of[ni], oi, vi]

    for i, node in enumerate(nodes):
        if i in drop_nodes:
            continue
        inputs = [map_ref(r) for r in node["inputs"]]
        if i in new_bias_nodes and len(inputs) == 2:
            inputs.append([index_of[("bias", i)], 0, 0])
        node["inputs"] = inputs
    graph["heads"] = [map_ref(r) for r in graph["heads"]]
    graph["nodes"] = new_nodes
    graph["arg_nodes"] = [j for j, n in enumerate(new_nodes)
                          if n["op"] == "null"]

    new_sym = sym_mod.load_json(json.dumps(graph))
    arg_names = set(new_sym.list_arguments())
    aux_names = set(new_sym.list_auxiliary_states())
    out_args = {k: nd_mod.array(v) for k, v in args.items()
                if k in arg_names}
    out_auxs = {k: nd_mod.array(v) for k, v in auxs.items()
                if k in aux_names}
    return new_sym, out_args, out_auxs


def fold_block(net, x):
    """One-call gluon deployment: HybridBlock -> BN-folded SymbolBlock.

    Runs `net` once on `x` to build its cached graph, exports it, folds
    every Conv+BN pair, and returns a gluon.SymbolBlock carrying the
    folded params — drop-in for inference (`folded(x)`).
    """
    import os
    import tempfile

    from .. import ndarray as nd_mod
    from ..gluon import SymbolBlock

    net.hybridize()
    net(x)                                  # trace the cached graph
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "m")
        net.export(prefix)
        loaded = nd_mod.load(prefix + "-0000.params")
        from .. import symbol as sym_mod
        s = sym_mod.load(prefix + "-symbol.json")
        args = {k.split(":", 1)[1]: v for k, v in loaded.items()
                if k.startswith("arg:")}
        auxs = {k.split(":", 1)[1]: v for k, v in loaded.items()
                if k.startswith("aux:")}
        fsym, fargs, fauxs = fold_batch_norm(s, args, auxs)
        sym_file = os.path.join(td, "folded-symbol.json")
        with open(sym_file, "w") as f:
            f.write(fsym.tojson())
        param_file = os.path.join(td, "folded.params")
        packed = {"arg:%s" % k: v for k, v in fargs.items()}
        packed.update({"aux:%s" % k: v for k, v in fauxs.items()})
        nd_mod.save(param_file, packed)
        param_names = set(fargs) | set(fauxs)
        data_names = [n for n in fsym.list_arguments()
                      if n not in param_names]
        return SymbolBlock.imports(sym_file, data_names, param_file)
