"""mx.contrib.symbol — the symbolic `_contrib_*` namespace, same
functions as `mx.sym.contrib`."""
from ..symbol import contrib as _c


def __getattr__(item):
    return getattr(_c, item)
