"""Legacy contrib autograd API (reference: contrib/autograd.py) — the
pre-`mx.autograd` spelling kept for old user code; everything forwards
to the modern tape in mxnet_tpu.autograd."""

import functools

from .. import autograd as _ag
from .. import ndarray as nd

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Flip global train mode; returns the previous value."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    return prev


def train_section():
    """`with train_section():` == `with autograd.record():`."""
    return _ag.record()


def test_section():
    """Recording scope with inference-mode operators."""
    return _ag.record(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    return _ag.backward(outputs, out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """Deprecated alias of backward (reference keeps it callable)."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Wrap func to return (gradients, loss) w.r.t. its NDArray args."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            idx = argnum if isinstance(argnum, (list, tuple)) else [argnum]
            variables = [args[i] for i in idx]
        for v in variables:
            assert isinstance(v, nd.NDArray), \
                "type of autograd input should be NDArray"
        grads = [nd.zeros_like(v) for v in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        backward([outputs] if isinstance(outputs, nd.NDArray) else outputs)
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Gradient-only version of grad_and_loss."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped
