"""mx.test_utils — testing helpers.

Reference: python/mxnet/test_utils.py (assert_almost_equal,
check_numeric_gradient, check_symbolic_forward/backward,
check_consistency, default_context, rand_ndarray, ...). The
cross-backend `check_consistency` here compares the CPU interpreter
against the compiled TPU path (SURVEY §4 takeaway (2)) when a TPU is
attached, else eager-vs-hybridized."""

import numbers

import numpy as np

from . import context
from . import ndarray as nd
from . import symbol as sym

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "random_arrays",
           "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency",
           "numeric_grad", "simple_forward", "assert_exception"]

_default_ctx = None


def default_context():
    return _default_ctx if _default_ctx is not None \
        else context.current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def _as_numpy(a):
    return a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(_as_numpy(a), _as_numpy(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    a, b = _as_numpy(a), _as_numpy(b)
    if not almost_equal(a, b, rtol, atol, equal_nan):
        index = np.unravel_index(
            np.argmax(np.abs(a - b) - atol - rtol * np.abs(b)), a.shape)
        rel = np.abs(a - b) / (np.abs(b) + atol)
        raise AssertionError(
            "Items are not equal (rtol=%g, atol=%g): max rel err %g at "
            "%s: %s=%r %s=%r" % (rtol, atol, np.nanmax(rel), str(index),
                                 names[0], a[index], names[1], b[index]))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution="uniform"):
    if stype != "default":
        from .ndarray import sparse
        return sparse.rand_sparse_ndarray(shape, stype, density=density,
                                          dtype=dtype)[0] \
            if hasattr(sparse, "rand_sparse_ndarray") else \
            nd.array(np.random.uniform(size=shape), dtype=dtype)
    if distribution == "normal":
        return nd.array(np.random.normal(size=shape), dtype=dtype)
    return nd.array(np.random.uniform(size=shape), dtype=dtype)


def random_arrays(*shapes):
    arrays = [np.array(np.random.randn(), dtype=np.float32) if len(s) == 0
              else np.random.randn(*s).astype(np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    """Bind a symbol with input arrays and run one forward."""
    shapes = {k: v.shape for k, v in inputs.items()}
    ex = sym_.simple_bind(ctx or default_context(), **shapes)
    for k, v in inputs.items():
        ex.arg_dict[k][:] = v
    ex.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in ex.outputs]
    return outputs[0] if len(outputs) == 1 else outputs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True, dtype=np.float32):
    """Finite-difference gradients of the executor's scalar-summed
    output wrt `location` (reference test_utils.numeric_grad)."""
    approx_grads = {k: np.zeros(v.shape, dtype=dtype)
                    for k, v in location.items()}
    for k, v in location.items():
        old_value = v.copy()
        for i in range(int(np.prod(v.shape)) if v.shape else 1):
            if v.shape:
                idx = np.unravel_index(i, v.shape)
            else:
                idx = ()
            v_p = old_value.copy()
            v_p[idx] += eps / 2
            executor.arg_dict[k][:] = v_p
            executor.forward(is_train=use_forward_train)
            f_p = sum(float(o.asnumpy().sum()) for o in executor.outputs)
            v_m = old_value.copy()
            v_m[idx] -= eps / 2
            executor.arg_dict[k][:] = v_m
            executor.forward(is_train=use_forward_train)
            f_m = sum(float(o.asnumpy().sum()) for o in executor.outputs)
            approx_grads[k][idx] = (f_p - f_m) / eps
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym_, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True,
                           ctx=None, grad_stype_dict=None, dtype=np.float64):
    """Finite-difference check of the symbol's gradients (reference
    check_numeric_gradient — SURVEY §4 load-bearing pattern (1))."""
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        arg_names = sym_.list_arguments()
        location = dict(zip(arg_names, location))
    location = {k: np.asarray(v, dtype=np.float32)
                for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = [k for k in sym_.list_arguments() if k in location]

    ex = sym_.simple_bind(ctx, grad_req={
        k: "write" if k in grad_nodes else "null"
        for k in sym_.list_arguments()},
        **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = v
    ex.forward(is_train=use_forward_train)
    ex.backward([nd.ones(o.shape) for o in ex.outputs])
    analytic = {k: ex.grad_dict[k].asnumpy() for k in grad_nodes
                if ex.grad_dict.get(k) is not None}
    numeric = numeric_grad(ex, {k: location[k] for k in grad_nodes},
                           eps=numeric_eps,
                           use_forward_train=use_forward_train)
    for k in grad_nodes:
        assert_almost_equal(analytic[k], numeric[k], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("analytic_%s" % k, "numeric_%s" % k))


def check_symbolic_forward(sym_, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None,
                           equal_nan=False, dtype=np.float32):
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym_.list_arguments(), location))
    ex = sym_.simple_bind(ctx, **{k: np.asarray(v).shape
                                  for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = np.asarray(v, dtype=dtype)
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = v
    ex.forward(is_train=False)
    for out, exp in zip(ex.outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return ex.outputs


def check_symbolic_backward(sym_, location, out_grads, expected,
                            rtol=1e-5, atol=None, aux_states=None,
                            grad_req="write", ctx=None, equal_nan=False,
                            dtype=np.float32):
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym_.list_arguments(), location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym_.list_arguments(), expected))
    ex = sym_.simple_bind(ctx, **{k: np.asarray(v).shape
                                  for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = np.asarray(v, dtype=dtype)
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = v
    ex.forward(is_train=True)
    ex.backward([g if isinstance(g, nd.NDArray) else nd.array(g)
                 for g in out_grads])
    for k, exp in expected.items():
        if ex.grad_dict.get(k) is None:
            continue
        assert_almost_equal(ex.grad_dict[k].asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return ex.grad_dict


def check_consistency(sym_, ctx_list=None, scale=1.0, rtol=1e-3, atol=1e-4,
                      arg_params=None):
    """Run the symbol on multiple contexts (or eager CPU vs jit TPU when
    ctx_list omitted) and compare outputs — the reference's CPU-vs-GPU
    harness (test_utils.check_consistency)."""
    if ctx_list is None:
        ctxs = [context.cpu()]
        if context.num_tpus():
            ctxs.append(context.tpu())
        ctx_list = [{"ctx": c} for c in ctxs]
    shapes = None
    outputs = []
    for spec in ctx_list:
        ctx = spec["ctx"] if isinstance(spec, dict) else spec
        shape_kwargs = {k: v for k, v in (spec.items()
                                          if isinstance(spec, dict) else [])
                        if k != "ctx" and isinstance(v, tuple)}
        if shapes is None:
            shapes = shape_kwargs
        ex = sym_.simple_bind(ctx, **shapes)
        if arg_params is None:
            np.random.seed(0)
            arg_params = {k: np.random.normal(
                size=ex.arg_dict[k].shape) * scale
                for k in ex.arg_dict}
        for k, v in arg_params.items():
            ex.arg_dict[k][:] = v
        ex.forward(is_train=False)
        outputs.append([o.asnumpy() for o in ex.outputs])
    for other in outputs[1:]:
        for a, b in zip(outputs[0], other):
            assert_almost_equal(a, b, rtol=rtol, atol=atol)
    return outputs


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("Did not raise %s" % exception_type.__name__)


def with_seed(seed=None):
    """Reproducible-RNG test decorator (reference
    tests/python/unittest/common.py with_seed): seeds numpy and
    mx.random per test from MXNET_TEST_SEED, the decorator argument, or
    a fresh draw — and prints the seed on failure so the run can be
    replayed (tools/flakiness_checker.py sets the env var)."""
    import functools
    import os

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            env = os.environ.get("MXNET_TEST_SEED")
            this_seed = seed if seed is not None else \
                (int(env) if env else np.random.randint(0, 2**31))
            np.random.seed(this_seed)
            from . import random as _mxrandom
            _mxrandom.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except BaseException:
                import logging
                logging.error(
                    "test %s failed with MXNET_TEST_SEED=%d — set the env "
                    "var to reproduce", fn.__name__, this_seed)
                raise
        return wrapper
    return deco


def retry(n):
    """Re-run a flaky test up to n times (reference common.py retry)."""
    import functools
    assert n > 0

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return fn(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
        return wrapper
    return deco


# ----------------------------------------------- reference helper set --
# (python/mxnet/test_utils.py) — the comparison/creation helpers the
# reference test-suite style leans on; download-based helpers are out of
# scope (zero-egress build).

def default_dtype():
    return np.float32


def get_atol(atol=None):
    return 1e-20 if atol is None else atol


def get_rtol(rtol=None):
    return 1e-5 if rtol is None else rtol


def get_etol(etol=None):
    return 0 if etol is None else etol


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Elementwise closeness with NaNs masked out of BOTH arrays."""
    a = np.copy(np.asarray(a))
    b = np.copy(np.asarray(b))
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return np.allclose(a, b, rtol=get_rtol(rtol), atol=get_atol(atol))


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None, names=("a", "b")):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError("%s and %s differ beyond tolerance "
                             "(NaNs ignored)" % names)


def assert_almost_equal_with_err(a, b, rtol=None, atol=None, etol=None,
                                 names=("a", "b")):
    """Allow a fraction etol of elements to violate the tolerance."""
    a = np.asarray(a)
    b = np.asarray(b)
    etol = get_etol(etol)
    bad = ~np.isclose(a, b, rtol=get_rtol(rtol), atol=get_atol(atol))
    frac = bad.mean() if bad.size else 0.0
    if frac > etol:
        raise AssertionError(
            "%s and %s: %.4f%% elements out of tolerance (etol %.4f%%)"
            % (names[0], names[1], frac * 100, etol * 100))


def find_max_violation(a, b, rtol=None, atol=None):
    """(index, relative-error) of the worst disagreement."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    diff = np.abs(a - b) - get_atol(atol) - get_rtol(rtol) * np.abs(b)
    idx = np.unravel_index(np.argmax(diff), a.shape) if a.size else ()
    rel = np.abs(a - b) / (np.abs(b) + get_atol(atol))
    return idx, float(rel[idx]) if a.size else 0.0


def compare_ndarray_tuple(t1, t2, rtol=None, atol=None):
    """Recursive closeness of (possibly nested) NDArray tuples."""
    if t1 is None or t2 is None:
        return
    if isinstance(t1, tuple):
        for a, b in zip(t1, t2):
            compare_ndarray_tuple(a, b, rtol, atol)
    else:
        assert_almost_equal(t1.asnumpy(), t2.asnumpy(), rtol=rtol or 1e-5,
                            atol=atol or 1e-8)


def compare_optimizer(opt1, opt2, shape, dtype="float32", w_stype="default",
                      g_stype="default", rtol=1e-4, atol=1e-5,
                      ntests=2):
    """Run both optimizers from identical state and require identical
    trajectories (reference compare_optimizer)."""
    rs = np.random.RandomState(0)
    w_np = rs.rand(*shape).astype(dtype)
    for i in range(ntests):
        g_np = rs.rand(*shape).astype(dtype) * 0.1
        w1 = nd.array(w_np.copy())
        w2 = nd.array(w_np.copy())
        g1 = nd.array(g_np)
        g2 = nd.array(g_np)
        s1 = opt1.create_state(0, w1)
        s2 = opt2.create_state(0, w2)
        opt1.update(0, w1, g1, s1)
        opt2.update(0, w2, g2, s2)
        compare_ndarray_tuple(s1 if isinstance(s1, tuple) else (s1,),
                              s2 if isinstance(s2, tuple) else (s2,),
                              rtol, atol)
        assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=rtol,
                            atol=atol)
        w_np = w1.asnumpy()


def create_vector(size, dtype=np.int64):
    """arange vector (reference create_vector for large-tensor tests)."""
    return nd.arange(0, size, dtype=dtype)


def create_2d_tensor(rows, columns, dtype=np.int64):
    a = np.arange(0, rows).reshape(rows, 1)
    return nd.array(np.broadcast_to(a, (rows, columns)), dtype=dtype)


def assign_each(input_, function):
    """Elementwise python-function application (reference assign_each)."""
    return np.vectorize(function)(np.asarray(input_))


def assign_each2(input1, input2, function):
    return np.vectorize(function)(np.asarray(input1), np.asarray(input2))


def collapse_sum_like(a, shape):
    """Sum `a` down to `shape` (inverse of broadcasting; reference
    collapse_sum_like)."""
    a = np.asarray(a)
    extra = a.ndim - len(shape)
    if extra:
        a = a.sum(axis=tuple(range(extra)))
    axes = tuple(i for i, s in enumerate(shape) if s == 1 and
                 a.shape[i] != 1)
    if axes:
        a = a.sum(axis=axes, keepdims=True)
    return a


def chi_square_check(generator, buckets, probs, nsamples=1000000):
    """Chi-square goodness-of-fit of a sampler against expected bucket
    probabilities; returns (statistic, p-value) (reference
    chi_square_check)."""
    from scipy import stats as sstats
    if isinstance(buckets[0], (list, tuple)):
        continuous = True
    else:
        continuous = False
    samples = np.asarray(generator(nsamples)).reshape(-1)
    counts = np.zeros(len(buckets))
    for i, b in enumerate(buckets):
        if continuous:
            lo, hi = b
            counts[i] = ((samples >= lo) & (samples < hi)).sum()
        else:
            counts[i] = (samples == b).sum()
    expected = np.asarray(probs, np.float64) * len(samples)
    stat, p = sstats.chisquare(counts, expected)
    return stat, p


def gen_buckets_probs_with_ppf(ppf, nbuckets):
    """Equal-probability buckets from a distribution's ppf (reference
    gen_buckets_probs_with_ppf)."""
    qs = np.linspace(0, 1, nbuckets + 1)
    edges = [ppf(q) for q in qs]
    buckets = [(edges[i], edges[i + 1]) for i in range(nbuckets)]
    probs = [1.0 / nbuckets] * nbuckets
    return buckets, probs


def create_sparse_array(shape, stype, density=0.5, dtype=None,
                        rsp_indices=None, data_init=None,
                        modifier_func=None, shuffle_csr_indices=False):
    """Random sparse NDArray (reference create_sparse_array, dense-backed
    here)."""
    from . import sparse as _sp
    out = _sp.rand_sparse_ndarray(shape, stype, density=density,
                                  dtype=dtype)
    return out[0] if isinstance(out, tuple) else out


def create_sparse_array_zd(shape, stype, density, **kwargs):
    """Sparse array allowing zero density (reference _zd variant)."""
    if density == 0:
        from . import sparse as _sp
        return _sp.zeros(stype, shape)
    return create_sparse_array(shape, stype, density=density, **kwargs)


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Time forward(+backward) of a symbol (reference check_speed)."""
    import time
    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        arg_shapes, _, _ = sym.infer_shape(**kwargs)
        rs = np.random.RandomState(0)
        location = {n: rs.rand(*s).astype(np.float32)
                    for n, s in zip(sym.list_arguments(), arg_shapes)}
    ex = sym.simple_bind(ctx, grad_req=grad_req,
                         **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = v
    # warmup
    ex.forward(is_train=(typ == "whole"))
    if typ == "whole":
        ex.backward([nd.ones(o.shape) for o in ex.outputs])
    for o in ex.outputs:
        o.wait_to_read()
    t0 = time.time()
    for _ in range(N):
        ex.forward(is_train=(typ == "whole"))
        if typ == "whole":
            ex.backward([nd.ones(o.shape) for o in ex.outputs])
    for o in ex.outputs:
        o.wait_to_read()
    return (time.time() - t0) / N


def discard_stderr():
    """Context manager silencing C-level stderr (reference
    discard_stderr)."""
    import contextlib
    import os as _os

    @contextlib.contextmanager
    def _cm():
        fd = _os.dup(2)
        devnull = _os.open(_os.devnull, _os.O_WRONLY)
        _os.dup2(devnull, 2)
        try:
            yield
        finally:
            _os.dup2(fd, 2)
            _os.close(devnull)
            _os.close(fd)
    return _cm()
