"""Framework-level utilities."""

__all__ = ["functionalize_block"]


def functionalize_block(net, example, is_train=False):
    """Trace an initialized HybridBlock into a pure graph function.

    Returns (graph_fn, data_names, args, aux) where
    graph_fn(arg_dict, aux_dict, rng_key) -> (outputs, new_aux),
    data_names are the traced input variable names, and args/aux are the
    network's parameter arrays (raw jax) split per the symbol's
    list_arguments / list_auxiliary_states. Used by __graft_entry__ and
    bench.py; mirrors what CachedOp does internally for hybridize."""
    from .executor import build_graph_fn

    net(example)  # materialize deferred-shape params
    data_syms, out_sym = net._get_graph(example)
    graph_fn = build_graph_fn(out_sym, is_train=is_train)
    arg_names = set(out_sym.list_arguments())
    aux_names = set(out_sym.list_auxiliary_states())
    all_params = {p.var().name: p.data()._data
                  for p in net.collect_params().values()}
    args = {k: v for k, v in all_params.items() if k in arg_names}
    aux = {k: v for k, v in all_params.items() if k in aux_names}
    return graph_fn, [s.name for s in data_syms], args, aux
