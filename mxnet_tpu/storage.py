"""Storage accounting.

Reference: src/storage/ — the pooled allocator with per-device usage
stats and profiler hooks (storage_manager.h, pooled_memory_storage).
On TPU the PJRT runtime owns allocation (arena + BFC inside the
runtime), so there is no user-space pool to manage; what this module
keeps is the OBSERVABILITY the reference pool provided:

  * `device_memory_stats()` — the runtime's live byte counters per
    device (PJRT `memory_stats`, the analogue of the pool's used/peak).
  * allocation tracking — opt-in (`start_tracking()`): every NDArray
    constructed while tracking is counted per context, decremented on
    collection, so leak hunts and per-phase footprints work like
    MXNET_PROFILE_MEMORY did against the reference pool.
"""

import threading
import weakref

from . import _fastenv
from .observability import core as _obs

__all__ = ["device_memory_stats", "start_tracking", "stop_tracking",
           "reset_stats", "summary", "publish_device_memory_gauges",
           "maybe_publish_device_memory_gauges"]

_TRACKING = False
_LOCK = threading.Lock()
_LIVE = {}      # ctx str -> [count, bytes]
_PEAK = {}      # ctx str -> peak bytes
_TOTAL = {}     # ctx str -> cumulative alloc count
_EPOCH = 0      # bumped by reset_stats; stale finalizers are ignored


def _note_alloc(arr):
    try:
        nbytes = arr._data.size * arr._data.dtype.itemsize
    except Exception:
        return
    key = str(arr._ctx)
    with _LOCK:
        live = _LIVE.setdefault(key, [0, 0])
        live[0] += 1
        live[1] += nbytes
        _PEAK[key] = max(_PEAK.get(key, 0), live[1])
        _TOTAL[key] = _TOTAL.get(key, 0) + 1
        epoch = _EPOCH
        live_bytes, peak_bytes = live[1], _PEAK[key]
    weakref.finalize(arr, _note_free, key, nbytes, epoch)
    if _obs.enabled():
        # tracked footprint as obs gauges: per-phase memory shows up in
        # the aggregate table / Prometheus next to the span timings
        _obs.gauge("mem.live_bytes.%s" % key, "bytes").set(live_bytes)
        _obs.gauge("mem.peak_bytes.%s" % key, "bytes").set(peak_bytes)


def _note_free(key, nbytes, epoch):
    with _LOCK:
        if epoch != _EPOCH:
            return      # counters were reset after this allocation
        live = _LIVE.get(key)
        if live:
            live[0] -= 1
            live[1] -= nbytes
            live_bytes = live[1]
        else:
            return
    if _obs.enabled():
        _obs.gauge("mem.live_bytes.%s" % key, "bytes").set(live_bytes)


def start_tracking():
    """Count NDArray allocations per context from this point on."""
    global _TRACKING
    _TRACKING = True


def stop_tracking():
    global _TRACKING
    _TRACKING = False


def reset_stats():
    global _EPOCH
    with _LOCK:
        _EPOCH += 1
        _LIVE.clear()
        _PEAK.clear()
        _TOTAL.clear()


def summary():
    """Tracked allocation stats: {ctx: {live, live_bytes, peak_bytes,
    total_allocs}} (only NDArrays created while tracking)."""
    with _LOCK:
        return {
            ctx: {"live": live[0], "live_bytes": live[1],
                  "peak_bytes": _PEAK.get(ctx, 0),
                  "total_allocs": _TOTAL.get(ctx, 0)}
            for ctx, live in _LIVE.items()}


def device_memory_stats(device=None):
    """Per-device byte counters from the PJRT runtime (bytes_in_use,
    peak_bytes_in_use, ... where the platform reports them)."""
    import jax
    devices = [device] if device is not None else jax.local_devices()
    out = {}
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        out[str(dev)] = stats or {}
    return out


def publish_device_memory_gauges():
    """Route the PJRT per-device byte counters into obs gauges
    (``mem.device.<stat>.<device>``, plus the derived
    ``mem.device.bytes_available.<device>`` = limit − in_use the
    brownout/headroom consumers read). One guarded branch with
    telemetry off; refreshed by ``profiler.dump()``, the cross-rank
    skew exchange, and — when ``MXNET_MEM_GAUGE_EVERY`` is set — every
    N trainer steps (:func:`maybe_publish_device_memory_gauges`).
    Returns the stats it published (empty when disabled)."""
    if not _obs.enabled():
        return {}
    stats = device_memory_stats()
    for dev, st in stats.items():
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in st:
                _obs.gauge("mem.device.%s.%s" % (key, dev),
                           "bytes").set(st[key])
        if "bytes_limit" in st and "bytes_in_use" in st:
            _obs.gauge("mem.device.bytes_available.%s" % dev,
                       "bytes").set(int(st["bytes_limit"])
                                    - int(st["bytes_in_use"]))
    return stats


_GAUGE_STEP = [0]


def maybe_publish_device_memory_gauges(step=None):
    """Step-cadence refresh of the ``mem.device.*`` gauges:
    ``MXNET_MEM_GAUGE_EVERY=N`` publishes every N steps (unset/0 keeps
    the old dump/skew-exchange-only cadence). Headroom-driven brownout
    and router decisions act on data at most N steps stale instead of
    one profiler-dump stale. One `_fastenv` read + one counter bump on
    the off path."""
    every = _fastenv.get("MXNET_MEM_GAUGE_EVERY")
    if not every:
        return {}
    try:
        every = int(every)
    except (TypeError, ValueError):
        return {}
    if every <= 0:
        return {}
    if step is None:
        _GAUGE_STEP[0] += 1
        step = _GAUGE_STEP[0]
    if step % every:
        return {}
    return publish_device_memory_gauges()
