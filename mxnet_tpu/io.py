"""Data iterators.

Reference: python/mxnet/io/io.py:180-790 (DataIter/DataBatch/DataDesc,
NDArrayIter, ResizeIter, PrefetchingIter) and src/io/ (CSVIter,
ImageRecordIter, MNISTIter registered C++ iterators surfaced as MXDataIter).

TPU-native design: iterators yield host-side numpy batches and convert to
device NDArrays at the boundary; batching is static-shape (pad +
discard/roll-over policies) so downstream jit never sees a ragged batch —
the TPU analogue of the reference's fixed-batch DataBatchLoader
(src/io/iter_batchloader.h). Prefetching uses a background thread like
dmlc::ThreadedIter (src/io/iter_prefetcher.h).
"""

import csv as _csv
import gzip
import os
import struct
import queue
import threading
import time
from collections import namedtuple

import numpy as np

from . import _fastenv
from . import ndarray as nd
from .ndarray import NDArray
from . import recordio
from .recordio import RecordCorrupt  # noqa: F401 (re-export)
from .observability import chaos as _chaos
from .observability import core as _obs


DEFAULT_IO_RETRIES = 3
DEFAULT_IO_BACKOFF_MS = 50.0
_IO_BACKOFF_CAP_S = 1.0


def _io_retries():
    """MXNET_IO_RETRIES: transient-read retries per operation
    (default 3; 0 disables retrying but keeps the enriched error)."""
    try:
        return max(int(_fastenv.get("MXNET_IO_RETRIES",
                                    DEFAULT_IO_RETRIES)), 0)
    except (TypeError, ValueError):
        return DEFAULT_IO_RETRIES


def _retry_read(fn, what, path=None, index=None):
    """Run one read, retrying transient failures (OSError — which
    includes injected ChaosError) with capped exponential backoff:
    MXNET_IO_RETRIES attempts after the first, MXNET_IO_BACKOFF_MS
    initial delay doubling up to 1 s. After exhaustion the error is
    re-raised naming the operation, path, and batch index — a dying
    pipeline must say WHERE it died. ``fn`` must be idempotent."""
    retries = _io_retries()
    try:
        delay = float(_fastenv.get("MXNET_IO_BACKOFF_MS",
                                   DEFAULT_IO_BACKOFF_MS)) / 1e3
    except (TypeError, ValueError):
        delay = DEFAULT_IO_BACKOFF_MS / 1e3
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError as exc:
            if attempt >= retries:
                raise IOError(
                    "%s failed after %d attempt(s) (path=%s, "
                    "batch=%s): %s: %s"
                    % (what, retries + 1, path, index,
                       type(exc).__name__, exc)) from exc
            if _obs.enabled():
                _obs.counter("io.retries").add(1)
                _obs.record_instant(
                    "io.retry", cat="io",
                    args={"what": what, "path": str(path),
                          "batch": index, "attempt": attempt + 1,
                          "error": str(exc)})
            time.sleep(min(delay, _IO_BACKOFF_CAP_S))
            delay *= 2


def _obs_batch(iter_obj, batch):
    """Per-batch telemetry: one counter bump + payload bytes. Called
    only when recording is on (the data path must stay free otherwise)."""
    _obs.counter("io.batches").add(1)
    total = 0
    for arr in (batch.data or []) + (batch.label or []):
        data = getattr(arr, "_data", None)
        nbytes = getattr(data, "nbytes", None)
        if nbytes:
            total += int(nbytes)
    if total:
        _obs.counter("io.bytes", "bytes").add(total)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "LibSVMIter", "RecordCorrupt"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape+dtype+layout of one input (io.py:70)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch(object):
    """One mini-batch (io.py:139)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (io.py:180)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        with _obs.span("io.next", cat="io", iter=type(self).__name__):
            if self.iter_next():
                batch = DataBatch(data=self.getdata(),
                                  label=self.getlabel(),
                                  pad=self.getpad(), index=self.getindex())
                if _obs.enabled():
                    _obs_batch(self, batch)
                return batch
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass

    # ------------------------------------------------------- cursors --
    # Exact-resume contract (docs/ROBUSTNESS.md "Elastic recovery"): a
    # restored iterator must yield the SAME remaining batch sequence
    # the saved one would have — zero skipped, zero replayed samples.
    # state_dict() is a cheap JSON-able position (cursor + epoch order),
    # never buffered data; elastic shard manifests persist it.

    def state_dict(self):
        """Resumable cursor for this iterator. Subclasses that own a
        position implement it; the base class refuses loudly so a
        checkpoint can never silently record a non-resumable source."""
        raise NotImplementedError(
            "%s does not support state_dict()/load_state_dict() — "
            "elastic/exact resume needs a cursor-capable iterator"
            % type(self).__name__)

    def load_state_dict(self, state):
        raise NotImplementedError(
            "%s does not support state_dict()/load_state_dict()"
            % type(self).__name__)


def _init_data(data, allow_empty, default_name):
    """io.py:493 — normalize to list of (name, numpy) pairs."""
    assert (data is not None) or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {default_name + "_%d" % i: d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            out[k] = v.asnumpy()
        else:
            out[k] = np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle + pad/discard/roll_over
    last-batch handling (io.py:560)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.batch_size = batch_size
        self.cursor = -self.batch_size
        self.num_data = self.idx.shape[0]
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        # roll_over carries the cached tail into the next epoch (io.py:640)
        if self.last_batch_handle == "roll_over" and self._cache_data is not None:
            self.cursor = -len(self._cache_data[0]) - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor <= self.num_data - self.batch_size
        return self.cursor < self.num_data

    def next(self):
        with _obs.span("io.next", cat="io", iter=type(self).__name__):
            if not self.iter_next():
                raise StopIteration
            data = self.getdata()
            label = self.getlabel()
            if self.cursor < 0:  # cached tail consumed
                self._cache_data = None
                self._cache_label = None
            if data[0].shape[0] != self.batch_size:
                if self.last_batch_handle == "roll_over":
                    # cache the tail for the next epoch (reference
                    # io.py next())
                    self._cache_data = [d.asnumpy() for d in data]
                    self._cache_label = [l.asnumpy() for l in label]
                    raise StopIteration
                # 'pad': wrap around with samples from the epoch start
                data = self._pad_batch(data, self.data)
                label = self._pad_batch(label, self.label)
            batch = DataBatch(data=data, label=label, pad=self.getpad(),
                              index=None)
            if _obs.enabled():
                _obs_batch(self, batch)
            return batch

    def _pad_batch(self, arrays, source):
        out = []
        for x, (_, v) in zip(arrays, source):
            pad = self.batch_size - x.shape[0]
            head = x.asnumpy()
            filler = v[self.idx[:pad]]
            out.append(nd.array(np.concatenate([head, filler])))
        return out

    def _getdata(self, data_source, cache):
        if self.cursor < 0:
            # roll_over start-of-epoch: cached tail + head of this epoch
            taken = self.cursor + self.batch_size
            out = []
            for c, (_, v) in zip(cache, data_source):
                out.append(nd.array(np.concatenate([c, v[self.idx[:taken]]])))
            return out
        end = min(self.cursor + self.batch_size, self.num_data)
        s = slice(self.cursor, end)
        return [nd.array(v[self.idx[s]]) for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data, self._cache_data)

    def getlabel(self):
        return self._getdata(self.label, self._cache_label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def _shuffle_data(self):
        np.random.shuffle(self.idx)

    def state_dict(self):
        """Exact mid-epoch position: the cursor plus this epoch's
        shuffle order (idx IS the epoch's sample permutation, so the
        restore replays neither the shuffle nor any sample), plus the
        roll_over tail cache when one is held. Arrays stay numpy —
        persistence layers JSON-ify at write time (elastic
        ``jsonable_cursor``)."""
        state = {"cursor": int(self.cursor), "idx": self.idx.copy()}
        if self._cache_data is not None:
            state["cache_data"] = [np.asarray(c)
                                   for c in self._cache_data]
            state["cache_label"] = [np.asarray(c)
                                    for c in self._cache_label]
        return state

    def load_state_dict(self, state):
        self.cursor = int(state["cursor"])
        self.idx = np.asarray(state["idx"], dtype=self.idx.dtype)
        if "cache_data" in state:
            self._cache_data = [
                np.asarray(c, dtype=v.dtype)
                for c, (_, v) in zip(state["cache_data"], self.data)]
            self._cache_label = [
                np.asarray(c, dtype=v.dtype)
                for c, (_, v) in zip(state["cache_label"], self.label)]
        else:
            self._cache_data = None
            self._cache_label = None


class ResizeIter(DataIter):
    """Resize epoch length of an inner iterator (io.py:351)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def state_dict(self):
        return {"cur": int(self.cur),
                "inner": self.data_iter.state_dict()}

    def load_state_dict(self, state):
        self.cur = int(state["cur"])
        self.data_iter.load_state_dict(state["inner"])
        self.current_batch = None


class PrefetchingIter(DataIter):
    """Background-thread prefetcher over one or more iterators (io.py:410)
    — the Python analogue of dmlc::ThreadedIter in iter_prefetcher.h."""

    class _Fetcher(threading.Thread):
        """One background fetcher per inner iterator: each order placed
        on the depth-1 `orders` queue produces one batch (or None at
        end-of-epoch) on `results` — queue backpressure replaces the
        reference's event-pair handshake."""

        _STOP = object()

        def __init__(self, it):
            super().__init__(daemon=True)
            self.it = it
            self.orders = queue.Queue(1)
            self.results = queue.Queue(1)
            self.pending = False
            self.start()

        def run(self):
            while True:
                order = self.orders.get()
                if order is self._STOP:
                    return
                try:
                    self.results.put(self.it.next())
                except StopIteration:
                    self.results.put(None)
                except Exception as exc:        # surfaced at take()
                    self.results.put(exc)

        def request(self):
            self.orders.put("fetch")
            self.pending = True

        def take(self):
            out = self.results.get()
            self.pending = False
            if isinstance(out, Exception):
                raise out
            return out

        def stop(self):
            if self.pending:
                self.results.get()
            self.orders.put(self._STOP)

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        self.iters = iters if isinstance(iters, list) else [iters]
        assert self.iters
        self.n_iter = len(self.iters)
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.current_batch = None
        self._drained = False
        self._fetchers = [self._Fetcher(it) for it in self.iters]
        # quiescent-point cursor: captured whenever every fetcher is
        # idle (inner iterators advanced exactly as far as the caller
        # consumed), i.e. BEFORE each prefetch order goes out — the
        # position an exact resume must restart from
        self._inner_cursor = self._snapshot_inner()
        for f in self._fetchers:
            f.request()

    def __del__(self):
        for f in getattr(self, "_fetchers", ()):
            f.stop()

    def _renamed_descs(self, which, renames):
        descs = []
        for i, it in enumerate(self.iters):
            for x in getattr(it, which):
                if isinstance(x, DataDesc):
                    # only full descs participate in renaming (tuple
                    # descs pass through untouched — reference parity)
                    name = x.name if renames is None \
                        else renames[i][x.name]
                    descs.append(DataDesc(name, x.shape, x.dtype))
                else:
                    descs.append(DataDesc(*x))
        return descs

    @property
    def provide_data(self):
        return self._renamed_descs("provide_data", self.rename_data)

    @property
    def provide_label(self):
        return self._renamed_descs("provide_label", self.rename_label)

    def reset(self):
        # drain any in-flight fetch before touching the inner iterators
        for f in self._fetchers:
            if f.pending:
                try:
                    f.take()
                except Exception:       # noqa: BLE001 — already seen
                    pass                # by the caller via iter_next
        for it in self.iters:
            it.reset()
        self._drained = False
        self._inner_cursor = self._snapshot_inner()
        for f in self._fetchers:
            f.request()

    def iter_next(self):
        if self._drained:
            # end-of-epoch (or a failed fetch) with no orders
            # outstanding: repeated calls stay False until reset()
            return False
        try:
            # the wait on the fetcher queue IS the input-pipeline stall
            # a training loop feels; surface it as its own phase
            with _obs.span("io.prefetch_wait", cat="io",
                           iters=self.n_iter):
                batches = [f.take() for f in self._fetchers]
        except Exception:
            self._drained = True        # reset() recovers the others
            raise
        ended = [b is None for b in batches]
        if any(ended):
            assert all(ended), \
                "Number of entry mismatches between iterators"
            self._drained = True
            return False
        assert len({b.pad for b in batches}) == 1, \
            "Different pad size between iterators"
        self.current_batch = DataBatch(
            [d for b in batches for d in b.data],
            [l for b in batches for l in b.label],
            batches[0].pad, batches[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        # fetchers are idle here: the inner cursors are exactly one
        # consumed-batch past the previous snapshot
        self._inner_cursor = self._snapshot_inner()
        for f in self._fetchers:
            f.request()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def _snapshot_inner(self):
        """Inner cursors at a quiescent point (no fetch in flight).
        Inners without cursor support snapshot as None — state_dict
        names them if a resume is ever requested, instead of failing
        every ordinary run up front."""
        out = []
        for it in self.iters:
            try:
                out.append(it.state_dict())
            except NotImplementedError:
                out.append(None)
        return out

    def state_dict(self):
        """Resume position = the last consumed batch. The in-flight
        prefetch does NOT advance it: snapshots are taken only while
        the fetchers are idle, so the saved cursor never skips the
        batch currently being prefetched."""
        missing = [type(it).__name__
                   for it, st in zip(self.iters, self._inner_cursor)
                   if st is None]
        if missing:
            raise NotImplementedError(
                "PrefetchingIter: inner iterator(s) %s do not support "
                "state_dict() — exact resume is impossible through "
                "them" % missing)
        return {"inner": list(self._inner_cursor)}

    def load_state_dict(self, state):
        # drain any in-flight fetch, rewind the inners, refill
        for f in self._fetchers:
            if f.pending:
                try:
                    f.take()
                except Exception:        # noqa: BLE001 — stale epoch
                    pass
        for it, st in zip(self.iters, state["inner"]):
            it.load_state_dict(st)
        self._drained = False
        self._inner_cursor = self._snapshot_inner()
        for f in self._fetchers:
            f.request()


class CSVIter(NDArrayIter):
    """CSV reader (src/io/iter_csv.cc registered as CSVIter). Loads the
    file host-side then batches like NDArrayIter (static shapes)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        def load(path):
            if _chaos.enabled():
                _chaos.fire("io.read", path=path)
            return np.loadtxt(path, delimiter=",", dtype=np.float32,
                              ndmin=2)
        data = _retry_read(lambda: load(data_csv), "csv read",
                           path=data_csv)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _retry_read(lambda: load(label_csv), "csv read",
                                path=label_csv)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard",
                         data_name=kwargs.get("data_name", "data"),
                         label_name=kwargs.get("label_name", "label"))


class LibSVMIter(NDArrayIter):
    """LibSVM sparse-format reader (src/io/iter_libsvm.cc). Parses into a
    dense array (TPU sparse divergence, SURVEY §7(a)); label supports
    multi-target files."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, **kwargs):
        data, labels = self._parse(data_libsvm, int(np.prod(data_shape)))
        if label_libsvm is not None:
            _, labels2 = self._parse_labels_only(label_libsvm)
            labels = labels2
        super().__init__(data, labels, batch_size=batch_size,
                         last_batch_handle="discard",
                         label_name=kwargs.get("label_name", "softmax_label"))

    @staticmethod
    def _parse(path, dim):
        # native C++ parser first (src/io/libsvm_scan.cc — the
        # reference's iter_libsvm.cc role); Python loop as fallback
        from . import _native
        parsed = _native.libsvm_parse(path, dim)
        if parsed is not None:
            return parsed
        rows, labels = [], []
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = np.zeros(dim, dtype=np.float32)
                for t in parts[1:]:
                    k, v = t.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        return np.stack(rows), np.asarray(labels, dtype=np.float32)

    @staticmethod
    def _parse_labels_only(path):
        labels = []
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if parts:
                    labels.append([float(x) for x in parts])
        return None, np.asarray(labels, dtype=np.float32).squeeze()


class MNISTIter(NDArrayIter):
    """MNIST idx-format reader (src/io/iter_mnist.cc). Reads the
    idx3-ubyte/idx1-ubyte (optionally .gz) files."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, **kwargs):
        img = _retry_read(lambda: self._read_idx(image), "idx read",
                          path=image)
        lbl = _retry_read(lambda: self._read_idx(label), "idx read",
                          path=label)
        img = img.astype(np.float32) / 255.0
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        super().__init__(img, lbl.astype(np.float32), batch_size=batch_size,
                         shuffle=shuffle, last_batch_handle="discard")

    @staticmethod
    def _read_idx(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


class ImageRecordIter(DataIter):
    """Threaded image-record pipeline (src/io/iter_image_recordio_2.cc).

    Reads RecordIO image records, decodes + augments (resize, crop,
    mirror, mean subtraction) in worker threads, emits fixed-shape NCHW
    batches. The C++ decode path is optional (mxnet_tpu.io uses PIL/npy
    payloads host-side); shapes are static for jit."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 rand_crop=False, rand_mirror=False, resize=-1,
                 label_width=1, preprocess_threads=4, round_batch=True,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.path_imgrec = path_imgrec
        self.record = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r") \
            if path_imgidx else recordio.MXRecordIO(path_imgrec, "r")
        self.data_shape = tuple(data_shape)
        self.shuffle = shuffle
        self.mean = np.array([mean_r, mean_g, mean_b],
                             dtype=np.float32).reshape(3, 1, 1)
        self.std = np.array([std_r, std_g, std_b],
                            dtype=np.float32).reshape(3, 1, 1)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self._records = self._load_all()
        self._order = np.arange(len(self._records))
        self.cursor = 0
        self.reset()

    def _load_all(self):
        out = []
        while True:
            def fetch():
                if _chaos.enabled():
                    _chaos.fire("io.read", path=self.path_imgrec,
                                record=len(out))
                return self.record.read()
            # a record read that hiccups (NFS blip, injected fault)
            # retries with backoff instead of killing the epoch
            rec = _retry_read(fetch, "record read",
                              path=self.path_imgrec, index=len(out))
            if rec is None:
                break
            header, payload = recordio.unpack(rec)
            out.append((header, payload))
        return out

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shp)]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self._order)
        self.cursor = 0

    def state_dict(self):
        """Mid-epoch record position: cursor + the epoch's (possibly
        shuffled) record order."""
        return {"cursor": int(self.cursor), "order": self._order.copy()}

    def load_state_dict(self, state):
        self.cursor = int(state["cursor"])
        self._order = np.asarray(state["order"],
                                 dtype=self._order.dtype)

    def _decode_one(self, header, payload):
        img = recordio._imdecode(payload)
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 2:
            img = np.stack([img] * 3, axis=-1)
        c, h, w = self.data_shape
        if self.resize > 0:
            img = _resize_hwc(img, self.resize)
        # crop to target h,w (center or random)
        ih, iw = img.shape[:2]
        if ih < h or iw < w:
            img = _resize_hwc(img, max(h, w))
            ih, iw = img.shape[:2]
        if self.rand_crop:
            y = np.random.randint(0, ih - h + 1)
            x = np.random.randint(0, iw - w + 1)
        else:
            y, x = (ih - h) // 2, (iw - w) // 2
        img = img[y:y + h, x:x + w]
        if self.rand_mirror and np.random.rand() < 0.5:
            img = img[:, ::-1]
        chw = img.transpose(2, 0, 1)[:c]
        chw = (chw - self.mean[:c]) / self.std[:c]
        label = header.label if np.ndim(header.label) else \
            np.float32(header.label)
        return chw, label

    def next(self):
        n = len(self._records)
        if self.cursor >= n:
            raise StopIteration
        with _obs.span("io.next", cat="io", iter=type(self).__name__):
            idxs = [self._order[(self.cursor + i) % n]
                    for i in range(self.batch_size)]
            pad = max(0, self.cursor + self.batch_size - n)
            batch_index = self.cursor // self.batch_size
            self.cursor += self.batch_size

            def assemble():
                # idempotent by construction (cursor advanced above):
                # a retried batch decodes the same records again
                if _chaos.enabled():
                    _chaos.fire("io.read", path=self.path_imgrec,
                                batch=batch_index)
                datas, labels = [], []
                for i in idxs:
                    header, payload = self._records[i]
                    d, l = self._decode_one(header, payload)
                    datas.append(d)
                    labels.append(l)
                return datas, labels

            datas, labels = _retry_read(
                assemble, "record batch decode",
                path=self.path_imgrec, index=batch_index)
            data = nd.array(np.stack(datas))
            label = nd.array(np.asarray(labels, dtype=np.float32))
            batch = DataBatch(data=[data], label=[label], pad=pad,
                              provide_data=self.provide_data,
                              provide_label=self.provide_label)
            if _obs.enabled():
                _obs_batch(self, batch)
            return batch


def _resize_hwc(img, short):
    """Bilinear resize shortest side to `short` (host-side numpy)."""
    h, w = img.shape[:2]
    if h < w:
        nh, nw = short, int(w * short / h)
    else:
        nh, nw = int(h * short / w), short
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    out = (img[y0][:, x0] * (1 - wy) * (1 - wx) +
           img[y1][:, x0] * wy * (1 - wx) +
           img[y0][:, x1] * (1 - wy) * wx +
           img[y1][:, x1] * wy * wx)
    return out.astype(np.float32)


class MXDataIter(DataIter):
    """Compatibility shim name for C++-registered iterators (io.py:790).
    In this framework native iterators are the Python classes above."""
    pass
