"""Executor — bound symbolic graphs, compiled to single XLA computations.

Reference: src/executor/graph_executor.cc (SimpleBind:1913, Bind:1995,
Forward:78, Backward:91) — there, the graph is executed node-by-node
through the dependency engine with a hand-built memory plan
(src/nnvm/plan_memory.cc) and manual op bulking (InitOpSegs:1288).

TPU-native design: binding lowers the WHOLE graph (and its backward) to
one jit-compiled XLA computation. XLA subsumes the reference passes:
memory planning (buffer assignment), inplace/addto detection (buffer
aliasing), op bulking (fusion), and the gradient pass (jax.vjp). The
train-mode path compiles forward+backward together so TPU sees a single
fused program per (shapes, dtypes) signature.
"""

import jax
import jax.numpy as jnp

from . import ops
from . import engine as _engine
from . import inspector as _inspector
from .base import MXNetError
from .observability import attribution as _obs_attr
from .observability import core as _obs
from .observability import membudget as _membudget
from .observability import recompile as _obs_recompile
from .symbol import OP_AUX

_META_ATTRS = ("__input_names__", "__shape__", "__dtype__", "__lr_mult__",
               "__wd_mult__", "__init__", "__aux__", "__ctx_group__",
               "__storage_type__")


def _clean_attrs(attrs):
    return {k: v for k, v in attrs.items() if not k.startswith("__")}


# ------------------------------------------------- gradient mirroring ----
# Reference: MXNET_BACKWARD_DO_MIRROR (src/nnvm/gradient.cc:285, switch at
# src/executor/graph_executor.cc:351-357) — recompute cheap forward
# activations in backward instead of storing them, trading FLOPs for
# memory. TPU-native mapping: jax.checkpoint (remat) around the traced
# graph. The policy mirrors the reference's mirror_fun granularity:
#   dots (default)  save MXU results (matmul/conv outputs), recompute
#                   elementwise/norm activations — the reference's
#                   "mirror everything but heavy ops" heuristic
#   full            save nothing that can be recomputed
#   none            disabled
def mirror_enabled(flags=None):
    """Resolve the mirror knob: explicit flag wins, then the reference's
    env var."""
    import os
    if flags:
        for key in ("backward_do_mirror", "do_mirror"):
            if key in flags:
                v = flags[key]
                return v if isinstance(v, bool) else str(v).lower() in (
                    "1", "true", "yes")
    return os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0").lower() in (
        "1", "true", "yes")


def _save_mxu_results(prim, *_, **__):
    # save outputs of MXU ops (matmul/conv — the reference's mirror pass
    # likewise never recomputes Convolution/FullyConnected, only cheap
    # activations, gradient.cc mirror_fun); everything else is
    # rematerialized in backward
    return getattr(prim, "name", str(prim)) in (
        "dot_general", "conv_general_dilated")


def _mirror_policy():
    import os
    name = os.environ.get("MXNET_MIRROR_POLICY", "dots")
    if name == "full":
        return None  # jax.checkpoint default: save nothing
    if name == "dots":
        return _save_mxu_results
    raise MXNetError(
        "MXNET_MIRROR_POLICY must be 'dots' or 'full', got %r" % name)


def apply_mirror(fn, enabled):
    """Wrap a traced graph function in jax.checkpoint when mirroring is
    on; identity otherwise."""
    if not enabled:
        return fn
    return jax.checkpoint(fn, policy=_mirror_policy())


def node_eval_fn(node, for_inference=False):
    """Pure fn(*input_arrays) for one graph node (used by eval_shape)."""
    op = ops.get(node.op)
    attrs = _clean_attrs(node.attrs)
    has_varargs, param_names = ops.op_dispatch_meta(op)
    if "is_train" in param_names:
        attrs.setdefault("is_train", False)
    if op.stateful_rng and "rng_key" in param_names:
        attrs.setdefault("rng_key", jax.random.PRNGKey(0))
    in_names = node.attrs.get("__input_names__")

    def fn(*arrays):
        if has_varargs:
            return op.fn(*arrays, **attrs)
        call = dict(attrs)
        if in_names:
            call.update({n: a for n, a in zip(in_names, arrays)})
        else:
            pnames = [p for p in param_names if p not in attrs]
            call.update({n: a for n, a in zip(pnames, arrays)})
        return op.fn(**call)

    return fn


def build_graph_fn(symbol, is_train, node_device=None):
    """Compile plan: returns fn(arg_dict, aux_dict, rng_key) ->
    (outputs_list, new_aux_dict).

    node_device: optional callable node -> jax.Device | None. When it
    returns a device, the node's outputs are constrained there with
    device_put — the model-parallel group2ctx placement pass
    (graph_executor.cc:997 AssignContext + cross_device_copy insertion:
    XLA/jax materializes the transfers at group boundaries)."""
    all_nodes = symbol._nodes
    nodes = symbol._active_nodes()
    out_refs = [(all_nodes[ni], oi) for ni, oi in symbol._outputs]

    def _place(node, arr):
        if node_device is None:
            return arr
        dev = node_device(node)
        return arr if dev is None else jax.device_put(arr, dev)

    def graph_fn(arg_arrays, aux_arrays, rng_key):
        # per-operator attribution (observability/attribution.py): when
        # telemetry is on at TRACE time, every node's primitives are
        # emitted under jax.named_scope(node.name) so the optimized
        # HLO's op_name metadata names the originating block/op even
        # after fusion. One guarded branch per trace when off.
        use_scopes = _obs_attr.ops_enabled()
        vals = {}
        aux_updates = {}
        key = rng_key
        for node in nodes:
            if node.is_var():
                name = node.name
                if name in arg_arrays:
                    vals[(id(node), 0)] = _place(node, arg_arrays[name])
                elif name in aux_arrays:
                    vals[(id(node), 0)] = _place(node, aux_arrays[name])
                else:
                    raise MXNetError("unbound variable %s" % name)
                continue
            op = ops.get(node.op)
            attrs = _clean_attrs(node.attrs)
            has_varargs, param_names = ops.op_dispatch_meta(op)
            if "is_train" in param_names:
                attrs["is_train"] = is_train
            if op.stateful_rng and "rng_key" in param_names:
                key, sub = jax.random.split(key)
                attrs["rng_key"] = sub
            ins = []
            for s, oi in node.inputs:
                src = s._nodes[s._outputs[0][0]]
                ins.append(_place(node, vals[(id(src), oi)]))
            in_names = node.attrs.get("__input_names__")

            def _eval_node(op=op, attrs=attrs, ins=ins,
                           has_varargs=has_varargs,
                           param_names=param_names, in_names=in_names):
                if has_varargs:
                    return op.fn(*ins, **attrs)
                call = dict(attrs)
                if in_names:
                    call.update({n: a for n, a in zip(in_names, ins)})
                else:
                    pnames = [p for p in param_names if p not in attrs]
                    call.update({n: a for n, a in zip(pnames, ins)})
                return op.fn(**call)

            if use_scopes:
                _obs_attr.note_scope(node.name)
                with jax.named_scope(node.name):
                    out = _eval_node()
            else:
                out = _eval_node()

            if _inspector.nan_guard_enabled():
                # MXNET_NAN_GUARD: host-side finite-ness check on every
                # node output, tagged with its producer (TensorInspector
                # parity, tensor_inspector.h NaNChecker). Staged at
                # trace time via jax.debug.callback.
                tag = "%s:%s" % (node.op, node.name)
                if isinstance(out, (tuple, list)):
                    out = type(out)(
                        _inspector.guard_value(o, tag) for o in out)
                else:
                    out = _inspector.guard_value(out, tag)
            if node.op in ("BatchNorm", "_contrib_SyncBatchNorm"):
                # fold running-stat update (reference mutates aux in-place,
                # src/operator/nn/batch_norm.cc; we return new values)
                y, mean, var = out
                vals[(id(node), 0)] = y
                if is_train and not node.attrs.get("use_global_stats", False):
                    mom = float(node.attrs.get("momentum", 0.9))
                    names = node.attrs.get("__input_names__", ())
                    for pname, stat in (("moving_mean", mean), ("moving_var", var)):
                        try:
                            idx = list(names).index(pname)
                        except ValueError:
                            continue
                        s, _ = node.inputs[idx]
                        aux_name = s._nodes[s._outputs[0][0]].name
                        old = aux_arrays[aux_name]
                        aux_updates[aux_name] = mom * old + (1 - mom) * stat
                continue
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for k, o in enumerate(outs):
                vals[(id(node), k)] = _place(node, o)

        outputs = []
        for node, oi in out_refs:
            outputs.append(vals[(id(node), oi)])
        return outputs, aux_updates

    return graph_fn


class Executor:
    """Bound executor (python/mxnet/executor.py wrapper semantics)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        from . import ndarray as nd
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = group2ctx or {}
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict = {k: v if isinstance(v, nd.NDArray) else nd.array(v)
                         for k, v in args.items()}
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict = args_grad or {}
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        self.aux_dict = aux_states or {}

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req)

        self._diff_args = [n for n in arg_names
                           if self._grad_req.get(n, "null") != "null"
                           and n in self.grad_dict]

        self.outputs = []
        self._saved_vjp = None
        # RNG-free graphs skip the per-forward host-side key split
        # (benchmark/opperf.py --dispatch)
        self._needs_rng = any(
            ops.get(n.op).stateful_rng
            for n in symbol._active_nodes() if not n.is_var())
        self._zero_key = None

        node_device = None
        if self._group2ctx:
            # model parallelism (graph_executor.cc:997): nodes carrying a
            # __ctx_group__ attr are pinned to group2ctx[group]'s device;
            # ungrouped nodes follow the default ctx. Arg/aux arrays move
            # to their owning node's device at bind time.
            dev_by_group = {g: c.jax_device
                            for g, c in self._group2ctx.items()}
            default_dev = ctx.jax_device if ctx is not None else None

            def node_device(node):
                group = node.attrs.get("__ctx_group__")
                return dev_by_group.get(group, default_dev)

            for node in symbol._active_nodes():
                if not node.is_var():
                    continue
                tgt = self.arg_dict.get(node.name)
                if tgt is None:
                    tgt = self.aux_dict.get(node.name)
                if tgt is not None:
                    tgt._data = jax.device_put(tgt._data,
                                               node_device(node))
            self._node_device = node_device
        fwd_infer = build_graph_fn(symbol, is_train=False,
                                   node_device=node_device)
        fwd_train = build_graph_fn(symbol, is_train=True,
                                   node_device=node_device)
        diff_names = tuple(self._diff_args)

        def infer_fn(arg_arrays, aux_arrays, key):
            outs, _ = fwd_infer(arg_arrays, aux_arrays, key)
            return outs

        do_mirror = mirror_enabled()

        def fwd_res_fn(diff_arrays, rest_arrays, aux_arrays, key):
            """Forward + pullback residuals. The returned vjp closure is a
            jax.tree_util.Partial (a pytree of residual arrays), so it
            crosses the jit boundary intact: backward() replays ONLY the
            transposed computation — custom head gradients cost no second
            forward (the reference executor also keeps fwd/bwd as two
            engine segments, graph_executor.cc RunOps). With
            MXNET_BACKWARD_DO_MIRROR the whole graph is rematerialized
            under the mirror policy, shrinking the residual set."""
            def f(diff):
                full = dict(rest_arrays)
                full.update(dict(zip(diff_names, diff)))
                outs, aux_up = fwd_train(full, aux_arrays, key)
                return outs, aux_up
            f = apply_mirror(f, do_mirror)
            outs, vjp, aux_up = jax.vjp(f, list(diff_arrays), has_aux=True)
            return outs, aux_up, vjp

        def bwd_fn(vjp, heads):
            (grads,) = vjp(heads)
            return grads

        self._jitted = node_device is None
        if node_device is None:
            # single-placement graphs compile whole-program; placed
            # (group2ctx) graphs run op-by-op so each segment can live on
            # its own device with transfers at group boundaries
            infer_fn = jax.jit(infer_fn)
            fwd_res_fn = jax.jit(fwd_res_fn)
            bwd_fn = jax.jit(bwd_fn)
        self._infer_fn = infer_fn
        self._fwd_res_fn = fwd_res_fn
        self._bwd_fn = bwd_fn
        self._obs_sig = None

    # ------------------------------------------------------------ run ---
    def forward(self, is_train=False, **kwargs):
        """is_train=True runs the forward program that also emits pullback
        residuals; backward() then replays only the transposed computation
        for whatever head gradients are supplied (defaults to ones). Use
        is_train=False for pure inference — the residual-free program."""
        from . import ndarray as nd
        from . import random as rnd
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data if isinstance(v, nd.NDArray) \
                    else jnp.asarray(v)
            else:
                raise MXNetError(
                    "forward got unknown argument %r (bound arguments: %s)"
                    % (k, sorted(self.arg_dict)))
        arg_arrays = {k: v._data for k, v in self.arg_dict.items()}
        aux_arrays = {k: v._data for k, v in self.aux_dict.items()}
        if self._needs_rng:
            key = rnd.next_key()
        else:
            if self._zero_key is None:
                self._zero_key = jax.random.PRNGKey(0)
            key = self._zero_key
        sig = None
        if _obs.enabled():
            sig = _obs_recompile.signature_of(
                arg_arrays.values(), train=is_train)
            _obs_recompile.note_call(
                "Executor[%s]" % self._symbol.list_outputs()[0], sig)
            self._obs_sig = sig
        fwd_span = _obs.span("forward", cat="step", executor=True,
                             train=is_train).start()
        if is_train:
            diff = [arg_arrays[n] for n in self._diff_args]
            rest = {k: v for k, v in arg_arrays.items()}
            if sig is not None and self._jitted \
                    and _obs_attr.ops_enabled():
                _obs_attr.register_program(
                    "Executor[%s].fwd" % self._symbol.list_outputs()[0],
                    sig, self._fwd_res_fn, (diff, rest, aux_arrays, key))
            if _membudget.enabled() and self._jitted:
                _membudget.preflight(
                    "Executor[%s].fwd" % self._symbol.list_outputs()[0],
                    self._fwd_res_fn, (diff, rest, aux_arrays, key),
                    signature=sig)
            try:
                outs, aux_up, vjp = self._fwd_res_fn(diff, rest,
                                                     aux_arrays, key)
            except Exception as exc:
                _membudget.note_oom(
                    "Executor[%s].fwd" % self._symbol.list_outputs()[0],
                    exc)
                raise
            self._saved_vjp = (vjp, outs)
            for name, val in aux_up.items():
                self.aux_dict[name]._data = val
        else:
            self._saved_vjp = None
            if sig is not None and self._jitted \
                    and _obs_attr.ops_enabled():
                _obs_attr.register_program(
                    "Executor[%s].infer"
                    % self._symbol.list_outputs()[0],
                    sig, self._infer_fn, (arg_arrays, aux_arrays, key))
            if _membudget.enabled() and self._jitted:
                _membudget.preflight(
                    "Executor[%s].infer"
                    % self._symbol.list_outputs()[0],
                    self._infer_fn, (arg_arrays, aux_arrays, key),
                    signature=sig)
            try:
                outs = self._infer_fn(arg_arrays, aux_arrays, key)
            except Exception as exc:
                _membudget.note_oom(
                    "Executor[%s].infer"
                    % self._symbol.list_outputs()[0], exc)
                raise
        _engine.sync_if_needed(outs)
        fwd_span.stop()
        self.outputs = [nd.NDArray(o, self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        from . import ndarray as nd
        if self._saved_vjp is None:
            raise MXNetError("backward called before forward(is_train=True)")
        bwd_span = _obs.span("backward", cat="step",
                             executor=True).start()
        vjp, outs = self._saved_vjp
        if out_grads is None:
            heads = [jnp.ones_like(o) for o in outs]
        else:
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            heads = [g._data if isinstance(g, nd.NDArray) else jnp.asarray(g)
                     for g in out_grads]
        cotangent = type(outs)(heads) if isinstance(outs, (tuple, list)) \
            else heads[0]
        if self._obs_sig is not None and self._jitted \
                and _obs_attr.ops_enabled():
            _obs_attr.register_program(
                "Executor[%s].bwd" % self._symbol.list_outputs()[0],
                self._obs_sig, self._bwd_fn, (vjp, cotangent))
        if _membudget.enabled() and self._jitted:
            _membudget.preflight(
                "Executor[%s].bwd" % self._symbol.list_outputs()[0],
                self._bwd_fn, (vjp, cotangent),
                signature=self._obs_sig)
        try:
            grads = self._bwd_fn(vjp, cotangent)
        except Exception as exc:
            _membudget.note_oom(
                "Executor[%s].bwd" % self._symbol.list_outputs()[0],
                exc)
            raise
        _engine.sync_if_needed(grads)
        for name, g in zip(self._diff_args, grads):
            req = self._grad_req.get(name, "write")
            tgt = self.grad_dict[name]
            if req == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g
        bwd_span.stop()

    # ------------------------------------------------------- utilities --
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._symbol.list_auxiliary_states()]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data.astype(self.arg_dict[k].dtype)
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %s" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._data = v._data
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %s" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """graph_executor.cc:876 Reshape — with jit, reshape is free: new
        shapes trigger a cached recompile keyed on the new signature."""
        from . import ndarray as nd
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        for name, shp in zip(self._symbol.list_arguments(), arg_shapes):
            cur = self.arg_dict[name]
            if tuple(cur.shape) != tuple(shp):
                self.arg_dict[name] = nd.zeros(shp, ctx=self._ctx, dtype=cur.dtype)
                if name in self.grad_dict and self.grad_dict[name] is not None:
                    self.grad_dict[name] = nd.zeros(shp, ctx=self._ctx,
                                                    dtype=cur.dtype)
        return self
