"""Runtime kernel compilation (reference: python/mxnet/rtc.py CudaModule
over NVRTC, src/common/rtc.cc).

TPU-native equivalent: runtime-compiled kernels are Pallas kernels, not
CUDA C. `PallasModule` fills the CudaModule role: wrap a python kernel
function and get a launchable Kernel. The CUDA-source API is kept for
source compatibility but raises — there is no NVRTC on TPU."""

import jax
from jax.experimental import pallas as pl

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel", "PallasModule"]


class CudaModule(object):
    """Source-compat stub: CUDA runtime compilation is unavailable on
    TPU. Use PallasModule with a Pallas kernel function instead."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "CudaModule (NVRTC) is not available in the TPU build; write "
            "the kernel as a Pallas function and wrap it in "
            "mxnet_tpu.rtc.PallasModule instead")


class CudaKernel(object):
    def __init__(self, *args, **kwargs):
        raise MXNetError("CudaKernel is not available in the TPU build")


class PallasModule(object):
    """Wraps Pallas kernel functions for launch, mirroring
    CudaModule.get_kernel.

    kernels: dict name -> (kernel_fn, out_shape_fn) where kernel_fn is a
    Pallas kernel body and out_shape_fn(*inputs) returns the
    jax.ShapeDtypeStruct (or list) of outputs.
    """

    def __init__(self, **kernels):
        self._kernels = kernels

    def get_kernel(self, name, signature=None):
        if name not in self._kernels:
            raise MXNetError("kernel %s not found; have %s"
                             % (name, sorted(self._kernels)))
        kernel_fn, out_shape_fn = self._kernels[name]

        class _Kernel(object):
            def launch(self, args, ctx=None, grid_dims=None,
                       block_dims=None, shared_mem=0):
                # block_dims/shared_mem are CUDA launch-config concepts;
                # Pallas expresses blocking via BlockSpecs in kernel_fn
                if block_dims is not None or shared_mem:
                    raise MXNetError(
                        "block_dims/shared_mem are not applicable to "
                        "Pallas kernels; express blocking with BlockSpec")
                datas = [a._data if hasattr(a, "_data") else a
                         for a in args]
                kw = {"grid": grid_dims} if grid_dims is not None else {}
                call = pl.pallas_call(
                    kernel_fn, out_shape=out_shape_fn(*datas),
                    # interpret off-TPU: the same kernel source runs on
                    # any backend (compiled for real on the chip)
                    interpret=jax.default_backend() != "tpu", **kw)
                return call(*datas)

        return _Kernel()
