"""User-defined modules in pure Python (reference:
python/mxnet/module/python_module.py).

PythonModule implements the BaseModule contract with no parameters and
no executor: subclasses fill in forward/backward. PythonLossModule is
the canonical use — a loss "layer" at the top of a pipeline (typically
inside a SequentialModule) whose backward emits the loss gradient
computed by a user function.
"""

import logging

import numpy as np

from .. import ndarray as nd
from ..io import DataDesc
from .base_module import BaseModule


class PythonModule(BaseModule):
    """A module whose computation is written directly in Python. It has
    no parameters (update/init are no-ops) — shape inference, binding
    and the forward/backward contract are what subclasses inherit."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super(PythonModule, self).__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # ------------------------------------------------------ properties --
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ------------------------------------------------------ parameters --
    def get_params(self):
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        pass

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        """Subclasses that produce predictions should override; by
        default a python module computes no metric."""

    # ----------------------------------------------------------- bind --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(*d)
            for d in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [
                l if isinstance(l, DataDesc) else DataDesc(*l)
                for l in label_shapes]
        self._output_shapes = self._compute_output_shapes()
        self.params_initialized = True

    def _compute_output_shapes(self):
        """Infer output shapes from data/label shapes. Must be
        overridden when outputs differ from the single data input."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """Loss head as a module: forward stores the prediction, backward
    produces the input gradient via `grad_func` (or the default
    cross-entropy-style pred-label gradient)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super(PythonLossModule, self).__init__(
            data_names, label_names, [name + "_output"], logger=logger)
        self._name = name
        assert len(self._data_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None:
            assert callable(grad_func)
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [DataDesc(self._name + "_output",
                         self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head; it takes no head gradient"
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(grad)
            self._scores_grad = grad
        else:
            # default: d/dscores of cross-entropy with integer labels
            # over softmaxed scores
            prob = nd.softmax(self._scores, axis=-1)
            one_hot = nd.one_hot(self._labels.astype("int32"),
                                 prob.shape[-1])
            self._scores_grad = (prob - one_hot) / prob.shape[0]

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
