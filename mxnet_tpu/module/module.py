"""Module — symbolic train/infer over a bound executor.

Reference: python/mxnet/module/module.py:40-759 (bind, init_params,
init_optimizer, forward/backward/update, save/load_checkpoint).

TPU-native: bind() compiles the symbol into ONE fused XLA program
(mxnet_tpu.executor.Executor) instead of a per-op engine schedule; data
parallelism over multiple devices happens through the kvstore's mesh
collectives rather than a DataParallelExecutorGroup splitting batches
host-side (executor_group.py:282 in the reference).
"""

import logging
import warnings

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from .. import kvstore as kvs
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..observability import chaos as _chaos
from ..observability import core as _obs
from ..observability import dist as _obs_dist
from ..observability import goodput as _obs_goodput
from ..observability import integrity as _integrity
from ..observability import recompile as _obs_recompile
from ..model import save_checkpoint, load_checkpoint
from .base_module import BaseModule, _check_input_names


class Module(BaseModule):
    """module.py:40."""

    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []

        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._grad_req = None

        self._exec = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------ static ctor --
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """module.py:157."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """module.py:186."""
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        logging.info('Saved checkpoint to "%s"', param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info('Saved optimizer state to "%s"', state_name)

    # ---------------------------------------------------------- props ---
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        self._assert_binded()
        return self._data_shapes

    @property
    def label_shapes(self):
        self._assert_binded()
        return self._label_shapes

    @property
    def output_shapes(self):
        self._assert_binded()
        kwargs = dict(self._data_shapes)
        if self._label_shapes:
            kwargs.update(dict(self._label_shapes))
        _, out_shapes, _ = self._symbol.infer_shape(**kwargs)
        return list(zip(self._output_names, out_shapes))

    # --------------------------------------------------------- params ---
    def get_params(self):
        self._assert_binded()
        assert self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """module.py:268."""
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        self._assert_binded()

        if self._arg_params is None:
            self._arg_params = {name: nd.zeros(arr.shape, dtype=arr.dtype)
                                for name, arr in self._exec_param_arrays().items()}
        if self._aux_params is None:
            self._aux_params = {name: nd.zeros(arr.shape, dtype=arr.dtype)
                                for name, arr in self._exec_aux_arrays().items()}

        attrs = self._symbol.attr_dict()

        def fill(name, arr, supplied):
            """One param: prefer the caller-supplied value; otherwise
            draw from the initializer (if the caller supplied a dict at
            all, a missing name is an error unless allow_missing)."""
            provided = None if supplied is None else supplied.get(name)
            if provided is not None:
                if provided is arr:
                    return
                if provided.shape != arr.shape:
                    raise RuntimeError(
                        "Parameter %s cannot be initialized from "
                        "loading. Shape mismatch, target %s vs loaded "
                        "%s" % (name, str(arr.shape),
                                str(provided.shape)))
                arr[:] = provided._data
                return
            if supplied is not None and not allow_missing:
                raise RuntimeError("%s is not presented" % name)
            if initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), arr)

        for pool, supplied in ((self._arg_params, arg_params),
                               (self._aux_params, aux_params)):
            for name in sorted(pool):
                fill(name, pool[name], supplied)

        self.params_initialized = True
        self._params_dirty = False
        self._exec.copy_params_from(self._arg_params, self._aux_params,
                                    allow_extra_params=True)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """module.py:341."""
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "set_params call ignored.", stacklevel=2)
            return
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=allow_extra)
        self.params_initialized = True
        self._params_dirty = True

    def _exec_param_arrays(self):
        return {n: self._exec.arg_dict[n] for n in self._param_names
                if n in self._exec.arg_dict}

    def _exec_aux_arrays(self):
        return dict(self._exec.aux_dict)

    def _sync_params_from_devices(self):
        for n in self._param_names:
            if n in self._exec.arg_dict:
                self._arg_params[n]._data = self._exec.arg_dict[n]._data
        for n, v in self._exec.aux_dict.items():
            self._aux_params[n]._data = v._data
        self._params_dirty = False

    # ----------------------------------------------------------- bind ---
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """module.py:364 — compiles the graph. The heavy passes the
        reference runs here (InferShape/Type, PlanMemory, AttachOpExecs —
        graph_executor.cc:461-1288) are all delegated to XLA at first
        execution; bind materializes buffers and the jitted callables."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        assert not (not for_training and inputs_need_grad)

        data_shapes = [x if isinstance(x, tuple) or hasattr(x, "name")
                       else tuple(x) for x in data_shapes]
        norm = []
        for x in data_shapes:
            if hasattr(x, "name"):
                norm.append((x.name, tuple(x.shape)))
            else:
                norm.append((x[0], tuple(x[1])))
        self._data_shapes = norm
        if label_shapes is not None:
            norml = []
            for x in label_shapes:
                if hasattr(x, "name"):
                    norml.append((x.name, tuple(x.shape)))
                else:
                    norml.append((x[0], tuple(x[1])))
            self._label_shapes = norml
        else:
            self._label_shapes = None

        shape_kwargs = dict(norm)
        if self._label_shapes:
            shape_kwargs.update(dict(self._label_shapes))
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_kwargs)
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()

        # variables may pin dtype via __dtype__ (int8 quantized weights)
        var_dtypes = {node.name: node.attrs["__dtype__"]
                      for node in self._symbol._active_nodes()
                      if node.is_var() and "__dtype__" in node.attrs}
        args = {n: nd.zeros(s, ctx=self._context[0],
                            dtype=var_dtypes.get(n, "float32"))
                for n, s in zip(arg_names, arg_shapes)}
        auxs = {n: nd.zeros(s, ctx=self._context[0])
                for n, s in zip(aux_names, aux_shapes)}
        grad_names = [n for n in arg_names
                      if n not in self._data_names + self._label_names
                      and n not in self._fixed_param_names] \
            if not inputs_need_grad else \
            [n for n in arg_names if n not in self._label_names
             and n not in self._fixed_param_names]
        args_grad = {n: nd.zeros(args[n].shape, ctx=self._context[0])
                     for n in grad_names} if for_training else None

        from ..executor import Executor
        self._exec = Executor(self._symbol, self._context[0], args,
                              args_grad=args_grad,
                              grad_req=grad_req if for_training else "null",
                              aux_states=auxs)
        self.binded = True

        # params loaded before bind (Module.load) land in the fresh executor
        if self.params_initialized and self._arg_params is not None:
            self._exec.copy_params_from(self._arg_params,
                                        self._aux_params or {},
                                        allow_extra_params=True)

        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    def _reset_bind(self):
        self.binded = False
        self._exec = None

    # ------------------------------------------------------- optimizer --
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """module.py:489 — sets up optimizer + kvstore.

        update_on_kvstore semantics (module.py:528): with a kvstore and a
        string optimizer, the optimizer runs inside the store (the
        reference would pickle it to PS servers)."""
        self._assert_binded()
        assert self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        kvstore_obj, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        # reference module.py:503-518: default rescale_grad = 1/batch_size
        # (scaled by num_workers under a dist kvstore)
        batch_size = self._data_shapes[0][1][0] if self._data_shapes else 1
        if kvstore_obj and "dist" in kvstore_obj.type:
            batch_size *= kvstore_obj.num_workers
        rescale_grad = 1.0 / max(batch_size, 1)

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore_obj:
            if self._compression_params:
                kvstore_obj.set_gradient_compression(self._compression_params)
            for i, name in enumerate(self._param_names):
                if name in self._arg_params:
                    kvstore_obj.init(i, self._arg_params[name])
            if update_on_kvstore:
                kvstore_obj.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # ---------------------------------------------------------- run -----
    def forward(self, data_batch, is_train=None):
        """module.py:585. Reshape-on-new-shape (module.py:600) is free
        under jit: a new signature recompiles into the cache."""
        self._assert_binded()
        assert self.params_initialized
        if is_train is None:
            is_train = self.for_training

        feed = {}
        for (name, _), arr in zip(self._data_shapes, data_batch.data):
            feed[name] = arr
        if self._label_shapes and data_batch.label:
            for (name, _), arr in zip(self._label_shapes, data_batch.label):
                feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        """module.py:627."""
        self._assert_binded()
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """module.py:646 — kvstore push/pull + optimizer step. Gradient
        traffic goes bucketed by default (parallel/fusion.py): keys in
        reverse-registration order, one fused dispatch per ~25 MB
        bucket instead of one per key; MXNET_KVSTORE_FUSION=0 restores
        the per-key loop."""
        self._assert_binded()
        assert self.params_initialized and self.optimizer_initialized
        with _obs.span("update", cat="step",
                       on_kvstore=bool(self._update_on_kvstore)):
            if _chaos.enabled():
                # chaos site: a "nan" rule poisons this step's grads
                _chaos.poison_ndarrays(
                    "module.grads",
                    [self._exec.grad_dict[n]
                     for n in self._param_names
                     if n in self._exec.grad_dict])
            if _chaos.step_guard_enabled() and not _chaos.all_finite(
                    [self._exec.grad_dict[n]._data
                     for n in self._param_names
                     if n in self._exec.grad_dict]):
                # skip push+update entirely: with update_on_kvstore the
                # weight update happens inside the store's push, so the
                # guard must gate BEFORE any gradient leaves the exec
                _chaos.count_skipped_step("module")
                skipped = True
            else:
                self._update_impl()
                skipped = False
        if _obs.enabled():
            _obs_recompile.step_boundary()
            _obs_dist.step_boundary(self._kvstore)
            if not skipped:
                # goodput ledger: a committed (non-guard-skipped) step
                _obs_goodput.note_step_commit()
        if _integrity.enabled():
            # same reverse-registration order as the fused grad path,
            # so vote evidence names the matching bucket/lane
            _integrity.step_boundary(
                [(i, self._exec.arg_dict[n]._data)
                 for i, n in enumerate(self._param_names)
                 if n in self._exec.grad_dict][::-1],
                kv=self._kvstore)

    def _update_impl(self):
        self._params_dirty = True
        from ..parallel import fusion
        fused = self._kvstore is not None and fusion.fusion_enabled()
        if fused:
            # reverse-registration (priority) order — the backward
            # pass produced these gradients last-layer-first
            pairs = [(i, name)
                     for i, name in enumerate(self._param_names)
                     if name in self._exec.grad_dict][::-1]
        if self._update_on_kvstore:
            if fused:
                if pairs:
                    self._kvstore.pushpull_fused(
                        [i for i, _ in pairs],
                        [self._exec.grad_dict[n] for _, n in pairs],
                        out=[self._exec.arg_dict[n] for _, n in pairs])
                return
            for i, name in enumerate(self._param_names):
                if name not in self._exec.grad_dict:
                    continue
                g = self._exec.grad_dict[name]
                w = self._exec.arg_dict[name]
                self._kvstore.push(i, g)
                self._kvstore.pull(i, out=w)
        else:
            if self._kvstore:
                if fused:
                    if pairs:
                        grads = [self._exec.grad_dict[n] for _, n in pairs]
                        self._kvstore.pushpull_fused(
                            [i for i, _ in pairs], grads, out=grads)
                else:
                    for i, name in enumerate(self._param_names):
                        if name not in self._exec.grad_dict:
                            continue
                        g = self._exec.grad_dict[name]
                        self._kvstore.push(i, g)
                        self._kvstore.pull(i, out=g)
            for i, name in enumerate(self._param_names):
                if name not in self._exec.grad_dict:
                    continue
                self._updater(i, self._exec.grad_dict[name],
                              self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        self._assert_binded()
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        self._assert_binded()
        assert self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if labels is None:
            return
        eval_metric.update_dict(
            dict(zip(self._label_names, labels)),
            dict(zip(self._output_names, self._exec.outputs)))

    # ---------------------------------------------------------- states --
    def get_states(self, merge_multi_context=True):
        self._assert_binded()
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        self._assert_binded()
        if states is not None:
            for n, s in zip(self._state_names, states):
                self._exec.arg_dict[n]._data = s._data
        else:
            for n in self._state_names:
                self._exec.arg_dict[n][:] = value

    def save_optimizer_states(self, fname):
        """module.py:728."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        """module.py:744."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        self._assert_binded()
        mon.install(self._exec)

    def reshape(self, data_shapes, label_shapes=None):
        """module.py:446."""
        self._assert_binded()
        self._data_shapes = [(x.name, tuple(x.shape)) if hasattr(x, "name")
                             else (x[0], tuple(x[1])) for x in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [(x.name, tuple(x.shape)) if hasattr(x, "name")
                                  else (x[0], tuple(x[1]))
                                  for x in label_shapes]
        kwargs = dict(self._data_shapes)
        if self._label_shapes:
            kwargs.update(dict(self._label_shapes))
        self._exec.reshape(**kwargs)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        if sparse_row_id_fn is not None and self._kvstore is not None:
            row_ids = sparse_row_id_fn(data_batch)
            for i, name in enumerate(self._param_names):
                if name in row_ids and name in self._exec.arg_dict:
                    self._kvstore.row_sparse_pull(
                        i, out=self._exec.arg_dict[name],
                        row_ids=row_ids[name])


def _create_kvstore(kvstore, num_device, arg_params):
    """model.py:69 _create_kvstore semantics."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape)
                               for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)
