"""BaseModule — the training-loop contract.

Reference counterpart: python/mxnet/module/base_module.py (fit:409,
score:176, predict:305, and the bind/init_params/init_optimizer
abstract surface). The SURFACE is the parity contract — every method
name, argument and return shape below matches the reference so Module
consumers port unchanged — but the loop internals are this repo's:
epochs drive a pull-one-ahead batch walk (iterators may reuse their
internal buffers per the MXNet contract, so the NEXT batch is fetched
only after the current one is consumed), metrics/callbacks ride the
shared BatchEndParam plumbing from model.py, and subclass hooks
(_prepare_epoch — SVRG's full-gradient refresh rides it) are explicit
rather than inlined special cases.
"""

import logging
import time

import numpy as np

from .. import io as mx_io
from .. import metric as mx_metric
from .. import ndarray as nd
from ..base import MXNetError
from ..model import BatchEndParam

# what a parameter (as opposed to a data/label input) looks like by
# name — used only to shrink the did-you-mean candidate list below
_PARAM_SUFFIXES = ("_weight", "_bias", "_gamma", "_beta")


def _check_input_names(symbol, names, typename, throw):
    """Every declared data/label/state name must be an argument of the
    bound symbol; an unknown name is almost always a typo, so the
    report lists the symbol's non-parameter arguments as candidates."""
    known = symbol.list_arguments()
    for name in names:
        if name in known:
            continue
        inputs = [a for a in known if not a.endswith(_PARAM_SUFFIXES)]
        msg = ("\033[91m%s_names=%s names '%s', which the symbol does "
               "not take as an argument. Symbol inputs that exist: "
               "%s\033[0m" % (typename, list(names), name,
                              ", ".join(inputs) or "<none>"))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _callbacks(cbs):
    """Normalize a callback argument (None | callable | list) to a
    flat list."""
    if cbs is None:
        return []
    if isinstance(cbs, (list, tuple)):
        return list(cbs)
    return [cbs]


_DRAINED = object()   # the data iterator has no batch left


class BaseModule(object):
    """The abstract train/eval/predict surface (reference
    base_module.py:64): subclasses supply bind/forward/backward/update
    and the parameter plumbing; this class owns the loops that drive
    them."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ---------------------------------------------------------- errors --
    def _assert_binded(self):
        if not self.binded:
            raise MXNetError("Module not binded. Call bind() first.")

    # -------------------------------------------------------- high-level --
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Inference over ``eval_data``, accumulated into
        ``eval_metric``; returns the metric's name/value pairs."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, mx_metric.EvalMetric):
            eval_metric = mx_metric.create(eval_metric)
        eval_metric.reset()
        nbatch = 0
        for batch in eval_data:
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            for cb in _callbacks(batch_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric,
                                 locals=locals()))
            nbatch += 1
        for cb in _callbacks(score_end_callback):
            cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                             eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Generator over ``(outputs_without_pad, nbatch, batch)``
        (reference base_module.py:262): each batch's outputs are
        sliced down to the real rows before they are yielded, so pad
        rows never leak into downstream accumulation."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            yield ([out[0:out.shape[0] - batch.pad]
                    for out in self.get_outputs()], nbatch, batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False, sparse_row_id_fn=None):
        """Forward over ``eval_data`` and collect outputs. A bare
        array runs as one batch; an iterator accumulates per-batch
        output lists, concatenated along batch when ``merge_batches``
        (a single merged output unwraps from its list unless
        ``always_output_list``)."""
        assert self.binded and self.params_initialized
        if isinstance(eval_data, (nd.NDArray, np.ndarray)):
            one = nd.array(eval_data) if isinstance(eval_data, np.ndarray) \
                else eval_data
            self.forward(mx_io.DataBatch([one]), is_train=False)
            return self.get_outputs()[0]
        if not isinstance(eval_data, mx_io.DataIter):
            raise ValueError("eval_data must be of type NDArray or DataIter")
        collected = [
            [out.copy() for out in outs]
            for outs, _, _ in self.iter_predict(eval_data,
                                                num_batch=num_batch,
                                                reset=reset)]
        if not collected or not merge_batches:
            return collected
        width = len(collected[0])
        if any(len(outs) != width for outs in collected):
            raise AssertionError(
                "Cannot merge batches, as num of outputs is not the same "
                "in mini-batches. Maybe bucketing is used?")
        merged = [nd.concatenate([outs[i] for outs in collected])
                  for i in range(width)]
        return merged if width > 1 or always_output_list else merged[0]

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The canonical training loop: bind, initialize, then
        ``num_epoch`` passes of step/metric/callback with optional
        per-epoch validation."""
        from .. import initializer as init_mod
        assert num_epoch is not None, "please specify number of epochs"

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if not isinstance(eval_metric, mx_metric.EvalMetric):
            eval_metric = mx_metric.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            started = time.time()
            eval_metric.reset()
            self._prepare_epoch(epoch - begin_epoch, train_data)
            self._run_epoch(train_data, eval_metric, epoch, monitor,
                            batch_end_callback, sparse_row_id_fn)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - started)

            # one consistent host-side parameter snapshot per epoch:
            # checkpoint callbacks and the device state must agree
            arg_snap, aux_snap = self.get_params()
            self.set_params(arg_snap, aux_snap)
            for cb in _callbacks(epoch_end_callback):
                cb(epoch, self.symbol, arg_snap, aux_snap)

            if eval_data is not None:
                for name, val in self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch):
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

            train_data.reset()

    def _prepare_epoch(self, epoch_offset, train_data):
        """Hook before each training epoch (e.g. SVRG full-gradient
        refresh); default no-op."""

    def _run_epoch(self, train_data, eval_metric, epoch, monitor,
                   batch_end_callback, sparse_row_id_fn):
        """One pass over train_data: step, metric, callbacks per batch.

        Walks the iterator one batch AHEAD of consumption — prepare()
        sees the upcoming batch (sparse row-id hints) while the
        current one is still the module's live input — but never pulls
        batch n+1 before batch n is fully consumed: MXNet-contract
        iterators may recycle their internal buffers on every next().
        """
        feed = iter(train_data)
        current = next(feed, _DRAINED)
        nbatch = 0
        while current is not _DRAINED:
            if monitor is not None:
                monitor.tic()
            self.forward_backward(current)
            self.update()
            if isinstance(current, list):
                self.update_metric(eval_metric,
                                   [b.label for b in current],
                                   pre_sliced=True)
            else:
                self.update_metric(eval_metric, current.label)
            upcoming = next(feed, _DRAINED)
            if upcoming is not _DRAINED:
                self.prepare(upcoming, sparse_row_id_fn=sparse_row_id_fn)
            if monitor is not None:
                monitor.toc_print()
            for cb in _callbacks(batch_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric,
                                 locals=locals()))
            nbatch += 1
            current = upcoming

    # ------------------------------------------------- symbol/params API --
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        """One flat file of ``arg:<name>`` / ``aux:<name>`` entries —
        the reference's checkpoint key convention, which load_params
        (and the reference's own loader) round-trips."""
        arg_params, aux_params = self.get_params()
        blob = {"arg:" + name: value for name, value in arg_params.items()}
        blob.update({"aux:" + name: value
                     for name, value in aux_params.items()})
        nd.save(fname, blob)

    def load_params(self, fname):
        args, auxs = {}, {}
        for key, value in nd.load(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                args[name] = value
            elif kind == "aux":
                auxs[name] = value
            else:
                raise ValueError(
                    "Invalid param file %s: key %r is neither arg: "
                    "nor aux:" % (fname, key))
        self.set_params(args, auxs)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        assert not merge_multi_context
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()


def _as_list(obj):
    """Kept under the reference helper's name for external callers:
    anything not already a list/tuple is wrapped (None included —
    unlike _callbacks, which treats None as 'no callbacks')."""
    return obj if isinstance(obj, (list, tuple)) else [obj]
