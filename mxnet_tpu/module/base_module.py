"""BaseModule — the training-loop contract.

Reference: python/mxnet/module/base_module.py (BaseModule.fit:409,
score:176, predict:305, forward_backward, bind/init_params/init_optimizer
abstract surface).
"""

import logging
import time

import numpy as np

from .. import io as mx_io
from .. import metric as mx_metric
from .. import ndarray as nd
from ..base import MXNetError
from ..model import BatchEndParam


def _check_input_names(symbol, names, typename, throw):
    """Verify every declared input name exists among the symbol's args."""
    args = symbol.list_arguments()
    param_suffixes = ("_weight", "_bias", "_gamma", "_beta")
    for name in names:
        if name in args:
            continue
        candidates = [a for a in args if not a.endswith(param_suffixes)]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) but " \
              "input with name '%s' is not found in symbol.list_arguments(). " \
              "Did you mean one of:\n\t%s\033[0m" % (
                  typename, str(names), name, "\n\t".join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


_END = object()   # sentinel: the data iterator is exhausted


class BaseModule(object):
    """base_module.py:64."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ---------------------------------------------------------- errors --
    def _assert_binded(self):
        if not self.binded:
            raise MXNetError("Module not binded. Call bind() first.")

    # -------------------------------------------------------- high-level --
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Run inference over eval_data and accumulate eval_metric."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, mx_metric.EvalMetric):
            eval_metric = mx_metric.create(eval_metric)
        eval_metric.reset()
        nbatch = 0
        for eval_batch in eval_data:
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            for callback in _as_list(batch_end_callback or []):
                callback(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals()))
            nbatch += 1
        for callback in _as_list(score_end_callback or []):
            callback(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """base_module.py:262."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False, sparse_row_id_fn=None):
        """Forward over the data and collect (optionally merged) outputs."""
        assert self.binded and self.params_initialized
        if isinstance(eval_data, (nd.NDArray, np.ndarray)):
            if isinstance(eval_data, np.ndarray):
                eval_data = nd.array(eval_data)
            self.forward(mx_io.DataBatch([eval_data]), is_train=False)
            return self.get_outputs()[0]
        if not isinstance(eval_data, mx_io.DataIter):
            raise ValueError("eval_data must be of type NDArray or DataIter")
        per_batch = [
            [out.copy() for out in outputs]
            for outputs, _, _ in self.iter_predict(eval_data,
                                                   num_batch=num_batch,
                                                   reset=reset)]
        if not per_batch or not merge_batches:
            return per_batch
        num_outputs = len(per_batch[0])
        if any(len(outs) != num_outputs for outs in per_batch):
            raise AssertionError(
                "Cannot merge batches, as num of outputs is not the same "
                "in mini-batches. Maybe bucketing is used?")
        merged = [nd.concatenate([outs[i] for outs in per_batch])
                  for i in range(num_outputs)]
        if num_outputs == 1 and not always_output_list:
            return merged[0]
        return merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The canonical training loop."""
        from .. import initializer as init_mod
        assert num_epoch is not None, "please specify number of epochs"

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if not isinstance(eval_metric, mx_metric.EvalMetric):
            eval_metric = mx_metric.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            self._prepare_epoch(epoch - begin_epoch, train_data)
            self._run_epoch(train_data, eval_metric, epoch, monitor,
                            batch_end_callback, sparse_row_id_fn)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            # sync a consistent host-side snapshot of the params
            arg_snap, aux_snap = self.get_params()
            self.set_params(arg_snap, aux_snap)
            for callback in _as_list(epoch_end_callback or []):
                callback(epoch, self.symbol, arg_snap, aux_snap)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

            train_data.reset()

    def _prepare_epoch(self, epoch_offset, train_data):
        """Hook before each training epoch (e.g. SVRG full-gradient
        refresh); default no-op."""

    def _run_epoch(self, train_data, eval_metric, epoch, monitor,
                   batch_end_callback, sparse_row_id_fn):
        """One pass over train_data: step, metric, callbacks per batch.

        The next batch is pulled only AFTER the current one is consumed —
        iterators following the MXNet contract may reuse their internal
        buffers on every next() call.
        """
        data_iter = iter(train_data)
        batch = next(data_iter, _END)
        nbatch = 0
        while batch is not _END:
            if monitor is not None:
                monitor.tic()
            self.forward_backward(batch)
            self.update()
            if isinstance(batch, list):
                self.update_metric(eval_metric, [b.label for b in batch],
                                   pre_sliced=True)
            else:
                self.update_metric(eval_metric, batch.label)
            upcoming = next(data_iter, _END)
            if upcoming is not _END:
                self.prepare(upcoming, sparse_row_id_fn=sparse_row_id_fn)
            if monitor is not None:
                monitor.toc_print()
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            nbatch += 1
            batch = upcoming

    # ------------------------------------------------- symbol/params API --
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        assert not merge_multi_context
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
