"""BucketingModule — variable-length inputs via per-bucket compiled programs.

Reference: python/mxnet/module/bucketing_module.py (sym_gen per bucket_key,
shared parameters across bucket executors).

TPU-native: each bucket is a separate static-shape jit compilation (XLA
requires static shapes — SURVEY §7 hard part (b)); parameters are shared
host-side and copied into whichever bucket executes. This is exactly the
bucket-and-pad strategy for dynamic shapes on TPU.
"""

import logging
import warnings

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule, _check_input_names
from .module import Module


class BucketingModule(BaseModule):
    """bucketing_module.py:40."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen

        symbol, data_names, label_names = sym_gen(default_bucket_key)
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)

        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._context = context
        self._work_load_list = work_load_list
        self._group2ctxs = group2ctxs
        self._compression_params = compression_params

        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None
        self._grad_req = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        self._assert_binded()
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        self._assert_binded()
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        self._assert_binded()
        return self._curr_module.output_shapes

    def get_params(self):
        self._assert_binded()
        assert self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._assert_binded()
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def get_states(self, merge_multi_context=True):
        self._assert_binded()
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        self._assert_binded()
        self._curr_module.set_states(states, value)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """bucketing_module.py:309 — binds the default bucket."""
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req

        symbol, data_names, label_names = self._sym_gen(self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        group2ctxs=self._group2ctxs,
                        compression_params=self._compression_params)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None,
                    grad_req=self._grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        if getattr(self, "_load_prefix", None):
            # restore a load()-requested checkpoint now that arrays exist
            self._curr_module.load_params(
                "%s-%04d.params" % (self._load_prefix, self._load_epoch))
            self.params_initialized = True
            self._load_prefix = None
        if getattr(self, "_preset_params", None):
            arg, aux = self._preset_params
            self._curr_module.init_params(allow_missing=True)
            self._curr_module.set_params(arg, aux, allow_missing=True,
                                         allow_extra=True)
            self.params_initialized = True
            # the executor holds the fresh values; mark dirty at THIS
            # level — get_params() pushes our flag down before syncing
            self._params_dirty = True
            self._preset_params = None

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """bucketing_module.py:376."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            group2ctxs=self._group2ctxs,
                            compression_params=self._compression_params)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False, shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._assert_binded()
        assert self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module) \
                    if hasattr(mod, "borrow_optimizer") else None
        self.optimizer_initialized = True

    def prepare(self, data_batch, sparse_row_id_fn=None):
        self._assert_binded()
        bucket_key = data_batch.bucket_key
        original_bucket_key = self._curr_bucket_key
        data_shapes = data_batch.provide_data
        label_shapes = data_batch.provide_label
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        self.switch_bucket(original_bucket_key, None, None)

    def forward(self, data_batch, is_train=None):
        """bucketing_module.py:465 — switch to the batch's bucket."""
        self._assert_binded()
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        # share latest params into this bucket's executor (sync the holder
        # bucket's device params to host first — update() steps land only
        # in its executor arg_dict)
        if self.params_initialized:
            src = self._buckets[self._default_bucket_key]
            if self._curr_module is not src:
                src._sync_params_from_devices()
                self._curr_module._arg_params = src._arg_params
                self._curr_module._aux_params = src._aux_params
                self._curr_module._exec.copy_params_from(
                    src._arg_params, src._aux_params, allow_extra_params=True)
                self._curr_module.params_initialized = True
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._assert_binded()
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._assert_binded()
        assert self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        if not self._curr_module.optimizer_initialized:
            self._curr_module._optimizer = \
                self._buckets[self._default_bucket_key]._optimizer
            self._curr_module._updater = \
                self._buckets[self._default_bucket_key]._updater
            self._curr_module._kvstore = \
                self._buckets[self._default_bucket_key]._kvstore
            self._curr_module._update_on_kvstore = \
                self._buckets[self._default_bucket_key]._update_on_kvstore
            self._curr_module.optimizer_initialized = True
        self._curr_module.update()
        # propagate updated params back to the default bucket holder
        if self._curr_bucket_key != self._default_bucket_key:
            src = self._curr_module
            dst = self._buckets[self._default_bucket_key]
            src._sync_params_from_devices()
            dst._arg_params = src._arg_params
            dst._aux_params = src._aux_params
            dst._exec.copy_params_from(src._arg_params, src._aux_params,
                                       allow_extra_params=True)

    def get_outputs(self, merge_multi_context=True):
        self._assert_binded()
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        self._assert_binded()
        assert self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._assert_binded()
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    @property
    def symbol(self):
        self._assert_binded()
        return self._curr_module.symbol

    def install_monitor(self, mon):
        self._assert_binded()
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    # ------------------------------------------------- checkpointing --
    def save_checkpoint(self, prefix, epoch, remove_amp_cast=False):
        """Save params + per-bucket symbols + the bucket list (reference
        bucketing_module.py save_checkpoint layout)."""
        assert self._buckets, "Empty BucketingModule cannot be saved"
        from .. import ndarray as nd
        import numpy as np
        self.save_params("%s-%04d.params" % (prefix, epoch))
        for bucket_key in self._buckets:
            symbol, _, _ = self._sym_gen(bucket_key)
            symbol.save("%s-%s-symbol.json" % (prefix, bucket_key))
        # non-integer bucket keys (tuples) can't serialize this way —
        # skip the reference-parity artifact then
        if all(isinstance(k, int) for k in self._buckets):
            nd.save("%s.buckets" % prefix,
                    nd.array(np.asarray(list(self._buckets),
                                        dtype=np.int32), dtype="int32"))

    @staticmethod
    def load(prefix, epoch, sym_gen=None, default_bucket_key=None,
             **kwargs):
        """Recreate a BucketingModule from save_checkpoint files; the
        original sym_gen must be supplied (symbols on disk are for
        inspection/inference tooling)."""
        assert sym_gen is not None, \
            "sym_gen is required to load a BucketingModule"
        assert default_bucket_key is not None
        mod = BucketingModule(sym_gen, default_bucket_key=default_bucket_key,
                              **kwargs)
        mod._load_prefix = prefix
        mod._load_epoch = epoch
        return mod

    @staticmethod
    def load_dict(sym_dict=None, sym_gen=None, default_bucket_key=None,
                  arg_params=None, aux_params=None, **kwargs):
        """Create a BucketingModule from in-memory dicts (reference
        load_dict contract): `sym_gen`/`default_bucket_key` define the
        module (sym_dict is accepted for signature parity — symbols are
        regenerated by sym_gen here), and arg/aux params install at
        bind time."""
        assert sym_gen is not None, \
            "sym_gen is required to build a BucketingModule"
        assert default_bucket_key is not None
        mod = BucketingModule(sym_gen,
                              default_bucket_key=default_bucket_key,
                              **kwargs)
        mod._preset_params = (arg_params or {}, aux_params or {})
        return mod
