"""Execution engine shim.

Reference: src/engine/ — ThreadedEnginePerDevice schedules every kernel as an
async op with read/write NDArray-var dependencies to hide CUDA launch latency
(include/mxnet/engine.h:117-318, src/engine/threaded_engine.cc:288).

TPU-native stance: XLA's runtime already executes dispatched computations
asynchronously and in dependency order (jax.Array futures), so a user-space
dependency scheduler for device kernels would only add latency. What remains
engine-shaped on this stack:
  * `wait_to_read` / `WaitForVar`  -> jax.Array.block_until_ready()
  * `WaitForAll`                   -> sync over live arrays
  * host-side async work (IO prefetch, checkpoint writes) -> a small thread
    pool with FIFO ordering per key, mirroring FnProperty queues
    (include/mxnet/engine.h:95-112).

`set_bulk_size` is kept as an API no-op: op bulking is what XLA fusion +
jit tracing do natively. `MXNET_ENGINE_TYPE=NaiveEngine` IS honored: it
makes every eager dispatch block until its outputs are materialized —
the same synchronous, deterministic-ordering debug mode the reference's
NaiveEngine provides (src/engine/naive_engine.cc). With
`MXNET_ENFORCE_DETERMINISM=1` the RNG key chain is pinned to the
partitionable threefry derivation so random streams are reproducible
across process topologies (the TPU compute itself is already
deterministic — there is no atomics-ordering nondeterminism to forbid,
which is what the reference flag guards against in cuDNN).
"""

import os
import queue
import threading

import jax

_BULK_SIZE = int(os.environ.get("MXNET_ENGINE_BULK_SIZE", "15"))
_ENGINE_TYPE = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
_ENFORCE_DETERMINISM = os.environ.get(
    "MXNET_ENFORCE_DETERMINISM", "0").lower() not in ("0", "", "false")

if _ENFORCE_DETERMINISM:  # pragma: no cover - env-dependent
    jax.config.update("jax_threefry_partitionable", True)


def engine_type():
    return _ENGINE_TYPE


def set_engine_type(name):
    """Switch engines at runtime (reference: MXNET_ENGINE_TYPE is
    read once at startup; runtime switching is a debugging convenience)."""
    global _ENGINE_TYPE
    prev = _ENGINE_TYPE
    _ENGINE_TYPE = name
    return prev


def is_naive():
    return _ENGINE_TYPE == "NaiveEngine"


def enforce_determinism():
    return _ENFORCE_DETERMINISM


def sync_outputs(arrays):
    """NaiveEngine semantics: the dispatch that produced `arrays` does
    not return until they are materialized on device."""
    for a in arrays:
        if hasattr(a, "block_until_ready"):
            a.block_until_ready()


_BACKEND_IS_CPU = None


def needs_serial_dispatch(arrays):
    """True when an eager dispatch must block before the next one: CPU
    backend with an output sharded over more than one device. Concurrent
    in-flight CPU executions containing collectives can interleave their
    rendezvous differently across the per-device threads and deadlock;
    TPU per-device streams execute programs in enqueue order (identical
    across devices from the single dispatching thread), so the real
    hardware path never pays this sync."""
    global _BACKEND_IS_CPU
    if _BACKEND_IS_CPU is None:
        # the backend is fixed once jax initializes (the library pins it
        # before first touch, _discover.py); default_backend() re-resolves
        # config every call — too slow for the dispatch path
        _BACKEND_IS_CPU = jax.default_backend() == "cpu"
    if not _BACKEND_IS_CPU:
        return False
    for a in arrays:
        s = getattr(a, "sharding", None)
        if s is not None and len(getattr(s, "device_set", ())) > 1:
            return True
    return False


def sync_if_needed(arrays):
    """The one dispatch-exit barrier every eager/compiled launch site
    calls: blocks when NaiveEngine is active (synchronous debug mode) or
    when `needs_serial_dispatch` flags a multi-device CPU output (see
    its docstring for the rendezvous-interleave hazard)."""
    if is_naive() or needs_serial_dispatch(arrays):
        sync_outputs(arrays)


class _Worker(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.q = queue.Queue()
        self.start()

    def run(self):
        while True:
            fn, done = self.q.get()
            try:
                fn()
            finally:
                done.set()


class Engine:
    """Host-side async executor with per-key FIFO ordering."""

    def __init__(self):
        self._workers = {}
        self._pending = []
        self._lock = threading.Lock()

    def push(self, fn, key="default"):
        """Run `fn` asynchronously; ops with the same key run in FIFO order
        (mirrors per-var queues in src/engine/threaded_engine.h:104-229)."""
        with self._lock:
            w = self._workers.get(key)
            if w is None:
                w = self._workers[key] = _Worker()
            done = threading.Event()
            self._pending.append(done)
            w.q.put((fn, done))
        return done

    def wait_for_all(self):
        with self._lock:
            pending, self._pending = self._pending, []
        for ev in pending:
            ev.wait()


_ENGINE = Engine()


def get():
    return _ENGINE


def push(fn, key="default"):
    return _ENGINE.push(fn, key)


def wait_for_var(arr):
    """Engine::WaitForVar — block until `arr` is materialized."""
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()


def wait_for_all():
    """MXNDArrayWaitAll: drain host-side queues and device work."""
    _ENGINE.wait_for_all()
    try:
        jax.effects_barrier()
    except Exception:  # pragma: no cover - older jax
        pass


def set_bulk_size(size):
    """Kept for API parity (engine op bulking == XLA fusion here)."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = size
    return prev


def bulk(size):
    """Context manager parity with mx.engine bulking (no-op under XLA)."""
    class _Bulk:
        def __enter__(self):
            self._prev = set_bulk_size(size)

        def __exit__(self, *a):
            set_bulk_size(self._prev)
    return _Bulk()
