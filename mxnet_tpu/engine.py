"""Execution engine shim.

Reference: src/engine/ — ThreadedEnginePerDevice schedules every kernel as an
async op with read/write NDArray-var dependencies to hide CUDA launch latency
(include/mxnet/engine.h:117-318, src/engine/threaded_engine.cc:288).

TPU-native stance: XLA's runtime already executes dispatched computations
asynchronously and in dependency order (jax.Array futures), so a user-space
dependency scheduler for device kernels would only add latency. What remains
engine-shaped on this stack:
  * `wait_to_read` / `WaitForVar`  -> jax.Array.block_until_ready()
  * `WaitForAll`                   -> sync over live arrays
  * host-side async work (IO prefetch, checkpoint writes) -> a small thread
    pool with FIFO ordering per key, mirroring FnProperty queues
    (include/mxnet/engine.h:95-112).

`set_bulk_size` / NaiveEngine toggles are kept as API no-ops: op bulking is
what XLA fusion + jit tracing do natively.
"""

import os
import queue
import threading

import jax

_BULK_SIZE = int(os.environ.get("MXNET_ENGINE_BULK_SIZE", "15"))


class _Worker(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.q = queue.Queue()
        self.start()

    def run(self):
        while True:
            fn, done = self.q.get()
            try:
                fn()
            finally:
                done.set()


class Engine:
    """Host-side async executor with per-key FIFO ordering."""

    def __init__(self):
        self._workers = {}
        self._pending = []
        self._lock = threading.Lock()

    def push(self, fn, key="default"):
        """Run `fn` asynchronously; ops with the same key run in FIFO order
        (mirrors per-var queues in src/engine/threaded_engine.h:104-229)."""
        with self._lock:
            w = self._workers.get(key)
            if w is None:
                w = self._workers[key] = _Worker()
            done = threading.Event()
            self._pending.append(done)
            w.q.put((fn, done))
        return done

    def wait_for_all(self):
        with self._lock:
            pending, self._pending = self._pending, []
        for ev in pending:
            ev.wait()


_ENGINE = Engine()


def get():
    return _ENGINE


def push(fn, key="default"):
    return _ENGINE.push(fn, key)


def wait_for_var(arr):
    """Engine::WaitForVar — block until `arr` is materialized."""
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()


def wait_for_all():
    """MXNDArrayWaitAll: drain host-side queues and device work."""
    _ENGINE.wait_for_all()
    try:
        jax.effects_barrier()
    except Exception:  # pragma: no cover - older jax
        pass


def set_bulk_size(size):
    """Kept for API parity (engine op bulking == XLA fusion here)."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = size
    return prev


def bulk(size):
    """Context manager parity with mx.engine bulking (no-op under XLA)."""
    class _Bulk:
        def __enter__(self):
            self._prev = set_bulk_size(size)

        def __exit__(self, *a):
            set_bulk_size(self._prev)
    return _Bulk()
