"""mx.np — NumPy-compatible array API.

Reference: python/mxnet/numpy/ (4.2k LoC) backed by src/operator/numpy/
(np_dot, tensordot, broadcast arithmetic, init, matrix ops, cumsum,
true_divide, np random).

TPU-native design: jax.numpy IS a NumPy-semantics array library, so
this layer is a faithful veneer: every function unwraps `ndarray`
operands to jax arrays, calls the jnp equivalent, and wraps the result.
Ops run on-device and fuse under jit like any other framework op. The
`ndarray` here interoperates with classic mx.nd.NDArray (shared _data)."""

import numpy as _onp

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .. import ndarray as _classic


class ndarray(_classic.NDArray):
    """NumPy-semantics array (reference numpy/multiarray.py ndarray)."""

    __slots__ = ()

    def __repr__(self):
        return "array(%s)" % _onp.array2string(self.asnumpy(),
                                               separator=", ")

    def __array__(self, dtype=None):
        out = self.asnumpy()
        return out.astype(dtype) if dtype is not None else out

    def __getitem__(self, key):
        out = super(ndarray, self).__getitem__(key)
        return _wrap(out._data) if isinstance(out, _classic.NDArray) else out

    def as_nd_ndarray(self):
        return _classic.NDArray(self._data, self._ctx)

    def tolist(self):
        return self.asnumpy().tolist()

    def item(self, *args):
        return self.asnumpy().item(*args)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _wrap(jnp.reshape(self._data, shape))

    def transpose(self, *axes):
        if len(axes) == 0:
            axes = None
        elif len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _wrap(jnp.transpose(self._data, axes))

    def sum(self, axis=None, dtype=None, keepdims=False):
        return _wrap(jnp.sum(self._data, axis=axis, dtype=dtype,
                             keepdims=keepdims))

    def mean(self, axis=None, dtype=None, keepdims=False):
        return _wrap(jnp.mean(self._data, axis=axis, dtype=dtype,
                              keepdims=keepdims))

    def max(self, axis=None, keepdims=False):
        return _wrap(jnp.max(self._data, axis=axis, keepdims=keepdims))

    def min(self, axis=None, keepdims=False):
        return _wrap(jnp.min(self._data, axis=axis, keepdims=keepdims))

    def astype(self, dtype, copy=True):
        return _wrap(self._data.astype(dtype))

    @property
    def T(self):
        return _wrap(jnp.transpose(self._data))


# arithmetic/comparison dunders must return mx.np.ndarray, not the
# classic NDArray the inherited operators construct
def _np_binop(jnp_fn, swap=False):
    def op(self, other):
        o = other._data if isinstance(other, _classic.NDArray) else other
        a, b = (o, self._data) if swap else (self._data, o)
        return _wrap(jnp_fn(a, b))
    return op


for _dunder, _fn, _swap in [
        ("__add__", jnp.add, False), ("__radd__", jnp.add, True),
        ("__sub__", jnp.subtract, False), ("__rsub__", jnp.subtract, True),
        ("__mul__", jnp.multiply, False), ("__rmul__", jnp.multiply, True),
        ("__truediv__", jnp.divide, False),
        ("__rtruediv__", jnp.divide, True),
        ("__floordiv__", jnp.floor_divide, False),
        ("__mod__", jnp.mod, False), ("__pow__", jnp.power, False),
        ("__rpow__", jnp.power, True),
        ("__matmul__", jnp.matmul, False),
        ("__eq__", jnp.equal, False), ("__ne__", jnp.not_equal, False),
        ("__lt__", jnp.less, False), ("__le__", jnp.less_equal, False),
        ("__gt__", jnp.greater, False),
        ("__ge__", jnp.greater_equal, False)]:
    setattr(ndarray, _dunder, _np_binop(_fn, _swap))
ndarray.__neg__ = lambda self: _wrap(jnp.negative(self._data))
ndarray.__abs__ = lambda self: _wrap(jnp.abs(self._data))
ndarray.__hash__ = None


def _wrap(data):
    return ndarray(jnp.asarray(data), current_context())


def _unwrap(x):
    if isinstance(x, _classic.NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(i) for i in x)
    return x


def array(object, dtype=None, ctx=None):
    from .._discover import ensure_backend
    ensure_backend()  # mx.np.array may be a process's first jax touch
    return ndarray(jnp.asarray(_unwrap(object), dtype=dtype),
                   ctx or current_context())


def _make(name, fn):
    def wrapper(*args, **kwargs):
        out_arr = kwargs.pop("out", None)
        args = [_unwrap(a) for a in args]
        kwargs = {k: _unwrap(v) for k, v in kwargs.items() if k != "ctx"}
        out = fn(*args, **kwargs)
        if out_arr is not None:
            # honour out= by writing the result into the given array
            out_arr._data = jnp.asarray(out).astype(out_arr.dtype)
            return out_arr
        if isinstance(out, (list, tuple)):
            return type(out)(_wrap(o) if hasattr(o, "shape") else o
                             for o in out)
        return _wrap(out) if hasattr(out, "shape") else out
    wrapper.__name__ = name
    wrapper.__doc__ = "mx.np.%s — jax.numpy-backed (reference " \
        "src/operator/numpy/)" % name
    return wrapper


_FUNCS = [
    # creation
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
    "eye", "identity", "zeros_like", "ones_like", "full_like", "meshgrid",
    "tril", "triu", "diag", "diagflat", "diagonal",
    # manipulation
    "reshape", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "split", "array_split", "hsplit", "vsplit",
    "dsplit", "tile", "repeat", "flip", "fliplr", "flipud", "roll",
    "rot90", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "ravel", "flatnonzero", "pad", "append",
    "unique", "trim_zeros",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "negative",
    "positive", "absolute", "abs", "fabs", "sign", "rint", "fix", "ceil",
    "floor", "trunc", "around", "round", "clip", "sqrt", "cbrt", "square",
    "reciprocal", "exp", "expm1", "exp2", "log", "log2", "log10", "log1p",
    "logaddexp", "logaddexp2", "sin", "cos", "tan", "arcsin", "arccos",
    "arctan", "arctan2", "hypot", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "degrees", "radians", "deg2rad", "rad2deg",
    "maximum", "minimum", "fmax", "fmin", "heaviside", "gcd", "lcm",
    "interp", "ldexp", "nan_to_num", "real", "imag", "conj", "angle",
    # reductions / scans
    "sum", "prod", "mean", "std", "var", "median", "average", "quantile",
    "percentile", "amax", "amin", "max", "min", "ptp", "cumsum", "cumprod",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmax", "nanmin",
    "argmax", "argmin", "nanargmax", "nanargmin", "count_nonzero",
    # products
    "dot", "vdot", "inner", "outer", "tensordot", "matmul", "einsum",
    "kron", "cross", "trace",
    # comparison / logic
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isnan",
    "isinf", "isfinite", "isposinf", "isneginf", "allclose", "isclose",
    "array_equal", "all", "any", "where", "nonzero", "argwhere",
    # sorting / searching
    "sort", "argsort", "partition", "argpartition", "searchsorted",
    "lexsort", "take", "take_along_axis", "choose", "compress", "extract",
    # misc
    "copysign", "signbit", "spacing", "nextafter", "bincount", "histogram",
    "digitize", "cov", "corrcoef", "convolve", "correlate", "gradient",
    "diff", "ediff1d", "floor_divide", "float_power", "may_share_memory",
    "shares_memory", "result_type", "can_cast", "promote_types",
]

_g = globals()
import warnings as _warnings
with _warnings.catch_warnings():
    # probing jnp attributes must not surface deprecation warnings at
    # import time (e.g. jnp.fix in jax 0.9)
    _warnings.simplefilter("ignore", DeprecationWarning)
    for _n in _FUNCS:
        if hasattr(jnp, _n):
            _g[_n] = _make(_n, getattr(jnp, _n))
# jnp.fix is deprecated (removed in jax 0.10); keep np.fix alive via
# trunc, which is the same round-toward-zero operation
fix = _make("fix", jnp.trunc)

# dtype aliases
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = jnp.bfloat16
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None


class _Linalg(object):
    """mx.np.linalg (reference numpy/linalg.py)."""

    def __getattr__(self, name):
        fn = getattr(jnp.linalg, name, None)
        if fn is None:
            raise AttributeError("np.linalg has no %s" % name)
        return _make("linalg." + name, fn)


linalg = _Linalg()


class _Random(object):
    """mx.np.random (reference numpy/random.py) — stateful seed over the
    framework's threefry key (mxnet_tpu.random)."""

    def _key(self):
        from .. import random as _rand
        return _rand.next_key()

    def seed(self, s):
        from .. import random as _rand
        _rand.seed(s)

    def uniform(self, low=0.0, high=1.0, size=None, dtype=None, ctx=None):
        size = size if size is not None else ()
        out = jax.random.uniform(self._key(), shape=_tup(size),
                                 minval=low, maxval=high,
                                 dtype=dtype or jnp.float32)
        return _wrap(out)

    def normal(self, loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
        size = size if size is not None else ()
        out = loc + scale * jax.random.normal(
            self._key(), shape=_tup(size), dtype=dtype or jnp.float32)
        return _wrap(out)

    def randint(self, low, high=None, size=None, dtype=None, ctx=None):
        if high is None:
            low, high = 0, low
        size = size if size is not None else ()
        out = jax.random.randint(self._key(), _tup(size), low, high,
                                 dtype=dtype or jnp.int32)
        return _wrap(out)

    def choice(self, a, size=None, replace=True, p=None, ctx=None):
        a = _unwrap(a)
        out = jax.random.choice(self._key(), a, shape=_tup(size or ()),
                                replace=replace,
                                p=_unwrap(p) if p is not None else None)
        return _wrap(out)

    def shuffle(self, x):
        data = jax.random.permutation(self._key(), x._data)
        x._data = data

    def rand(self, *shape):
        return self.uniform(size=shape)

    def randn(self, *shape):
        return self.normal(size=shape)

    def multinomial(self, n, pvals, size=None):
        out = jax.random.multinomial(
            self._key(), n, jnp.asarray(_unwrap(pvals)),
            shape=_tup(size) if size is not None else None)
        return _wrap(out)

    def gamma(self, shape=1.0, scale=1.0, size=None, dtype=None, ctx=None):
        size = size if size is not None else ()
        out = scale * jax.random.gamma(self._key(), shape,
                                       shape=_tup(size))
        return _wrap(out)

    def exponential(self, scale=1.0, size=None, ctx=None):
        size = size if size is not None else ()
        return _wrap(scale * jax.random.exponential(self._key(),
                                                    shape=_tup(size)))


def _tup(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


random = _Random()


def shape(a):
    return _unwrap(a).shape


def ndim(a):
    return _unwrap(a).ndim


def size(a):
    return int(_unwrap(a).size)
