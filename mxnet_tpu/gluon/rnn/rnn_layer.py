"""Gluon fused recurrent layers (RNN / LSTM / GRU).

Reference API: python/mxnet/gluon/rnn/rnn_layer.py:278-280 — layers
concatenate their per-layer i2h/h2h parameters into the flat fused-RNN
parameter vector (`_rnn_param_concat`) and call the RNN op
(src/operator/rnn.cc). Here the op is a lax.scan (ops/nn.py:rnn), so one
hybridized layer compiles to a single XLA while-loop with MXU matmul
body — the TPU analogue of cuDNN's fused RNN kernels.
"""

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super(_RNNLayer, self).__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4,
                       "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][: self._dir]:
                self._register_param(
                    "%s%d_i2h_weight" % (j, i), (ng * nh, ni),
                    i2h_weight_initializer)
                self._register_param(
                    "%s%d_h2h_weight" % (j, i), (ng * nh, nh),
                    h2h_weight_initializer)
                self._register_param(
                    "%s%d_i2h_bias" % (j, i), (ng * nh,),
                    i2h_bias_initializer)
                self._register_param(
                    "%s%d_h2h_bias" % (j, i), (ng * nh,),
                    h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def _deferred_infer_shape(self, *args):
        """Shape inference can't run backward through the flat-param
        concat, so fill the per-layer weight shapes straight from the
        input's channel dim."""
        inputs = args[0]
        input_size = inputs.shape[-1]
        self._input_size = input_size
        ng, nh = self._gates, self._hidden_size
        ni = input_size
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                getattr(self, "%s%d_i2h_weight" % (j, i)).shape = \
                    (ng * nh, ni)
            ni = nh * self._dir

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name="%sh0_%d" % (self.prefix, i), **info))
        return states

    def hybrid_forward(self, F, inputs, states=None, **kwargs):
        # states=None -> the fused RNN op synthesizes zero initial states
        # (no batch-size constant baked into hybridized graphs)
        skip_states = states is None
        if not skip_states and isinstance(states, type(inputs)):
            states = [states]
        out = self._forward_kernel(F, inputs, states, **kwargs)
        return out[0] if skip_states else out

    def _flat_params(self, F, kwargs):
        order = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                order.append(kwargs["%s%d_i2h_weight" % (j, i)])
                order.append(kwargs["%s%d_h2h_weight" % (j, i)])
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                order.append(kwargs["%s%d_i2h_bias" % (j, i)])
                order.append(kwargs["%s%d_h2h_bias" % (j, i)])
        flat = [F.reshape(p, shape=(-1,)) for p in order]
        if len(flat) == 1:
            return flat[0]
        return F.concat(*flat, dim=0)

    def _forward_kernel(self, F, inputs, states, **kwargs):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        params = self._flat_params(F, kwargs)
        rnn_args = states if states is not None else []
        rnn = F.RNN(inputs, params, *rnn_args,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, bidirectional=self._dir == 2,
                    p=self._dropout, state_outputs=True, mode=self._mode)
        if self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh or ReLU."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super(RNN, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer,
            "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super(LSTM, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super(GRU, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
