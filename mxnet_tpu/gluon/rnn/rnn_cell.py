"""Gluon recurrent cells.

Reference API: python/mxnet/gluon/rnn/rnn_cell.py (RecurrentCell,
RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
ModifierCell, ZoneoutCell, ResidualCell, BidirectionalCell).

TPU notes: cells are step functions; `unroll` builds a python-unrolled
graph which XLA fuses per step. For long sequences prefer the fused
`gluon.rnn.RNN/LSTM/GRU` layers (ops/nn.py RNN — a lax.scan).
"""

from ... import ndarray as nd
from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _gathered_state_info(cells, batch_size):
    return [info for c in cells for info in c.state_info(batch_size)]


def _gathered_begin_state(cells, **kwargs):
    return [s for c in cells for s in c.begin_state(**kwargs)]


def _step_through(cells, inputs, states):
    """Feed one step through a stack of cells, threading the state
    window each cell owns; returns (output, flat next states)."""
    cursor, collected = 0, []
    for cell in cells:
        width = len(cell.state_info())
        inputs, nxt = cell(inputs, states[cursor:cursor + width])
        cursor += width
        collected.extend(nxt)
    return inputs, collected


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Returns (inputs, time_axis, F, batch_size)."""
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        seq = list(inputs)
        F = _get_F(seq[0])
        batch = seq[0].shape[0] if hasattr(seq[0], "shape") and \
            seq[0].shape else 0
        if merge is True:
            return F.stack(*seq, axis=axis), axis, F, batch
        return seq, axis, F, batch
    F = _get_F(inputs)
    batch = 0
    if hasattr(inputs, "shape") and inputs.shape:
        batch = inputs.shape[batch_axis]
        if length is None:
            length = inputs.shape[axis]
    if merge is False:
        seq = F.split(inputs, num_outputs=length, axis=axis,
                      squeeze_axis=True)
        if not isinstance(seq, (list, tuple)):
            seq = [seq]
        return list(seq), axis, F, batch
    return inputs, axis, F, batch


def _get_F(x):
    from ... import symbol
    from ... import ndarray
    return symbol if isinstance(x, symbol.Symbol) else ndarray


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    """Zero out positions past valid_length (stacks step lists first,
    mirroring the reference helper)."""
    assert valid_length is not None
    if isinstance(data, (list, tuple)):
        data = F.stack(*data, axis=time_axis)
    outputs = F.SequenceMask(data, sequence_length=valid_length,
                             use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = F.split(outputs, num_outputs=length, axis=time_axis,
                          squeeze_axis=True)
        if not isinstance(outputs, (list, tuple)):
            outputs = [outputs]
        else:
            outputs = list(outputs)
    return outputs


class RecurrentCell(Block):
    """Abstract cell (gluon/rnn/rnn_cell.py:81)."""

    def __init__(self, prefix=None, params=None):
        super(RecurrentCell, self).__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            spec = dict(kwargs) if info is None else {**info, **kwargs}
            states.append(func(
                name="%sbegin_state_%d" % (self._prefix,
                                           self._init_counter), **spec))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unrolls the cell `length` steps (gluon/rnn/rnn_cell.py:218)."""
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs,
                                                       layout, False)
        begin_state = self._get_begin_state(F, begin_state, inputs,
                                            batch_size)
        states = begin_state
        outputs, state_history = [], []
        track = valid_length is not None
        for step_in in inputs[:length]:
            step_out, states = self(step_in, states)
            outputs.append(step_out)
            if track:
                state_history.append(states)
        if track:
            # per-row final state = the state at that row's last VALID
            # step, not the last unrolled one
            states = [F.SequenceLast(F.stack(*per_state, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
                      for per_state in zip(*state_history)]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis,
                bool(merge_outputs))
        elif merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def _get_begin_state(self, F, begin_state, inputs, batch_size):
        if begin_state is None:
            from ... import ndarray
            if F is ndarray:
                bs = batch_size if isinstance(batch_size, int) else 0
                begin_state = self.begin_state(batch_size=bs,
                                               func=ndarray.zeros)
            else:
                from ... import symbol
                begin_state = self.begin_state(
                    func=lambda name, **kw: symbol.var(name))
        return begin_state

    def __call__(self, inputs, states):
        self._counter += 1
        return super(RecurrentCell, self).__call__(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError()

    def _alias(self):
        return "recurrent_cell"


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Hybridizable cell."""

    def __init__(self, prefix=None, params=None):
        super(HybridRecurrentCell, self).__init__(prefix=prefix,
                                                  params=params)

    def forward(self, inputs, states):
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()

    def _declare_gate_params(self, hidden_size, input_size, n_gates,
                             inits):
        """The i2h/h2h weight+bias quartet, gate-fused along the
        leading axis (n_gates * hidden rows — one MXU matmul covers
        every gate)."""
        rows = n_gates * hidden_size
        i2h_w, h2h_w, i2h_b, h2h_b = inits
        for attr, shape, init in (
                ("i2h_weight", (rows, input_size), i2h_w),
                ("h2h_weight", (rows, hidden_size), h2h_w),
                ("i2h_bias", (rows,), i2h_b),
                ("h2h_bias", (rows,), h2h_b)):
            setattr(self, attr, self.params.get(
                attr, shape=shape, init=init, allow_deferred_init=True))

    def _nc_state_info(self, batch_size, count):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"} for _ in range(count)]

    @staticmethod
    def _fc_pair(F, prefix, inputs, prev, weights, width):
        i2h_weight, h2h_weight, i2h_bias, h2h_bias = weights
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=width, name=prefix + "i2h")
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias,
                               num_hidden=width, name=prefix + "h2h")
        return i2h, h2h


class RNNCell(HybridRecurrentCell):
    """Elman cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(RNNCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self._declare_gate_params(
            hidden_size, input_size, 1,
            (i2h_weight_initializer, h2h_weight_initializer,
             i2h_bias_initializer, h2h_bias_initializer))

    def state_info(self, batch_size=0):
        return self._nc_state_info(batch_size, 1)

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h, h2h = self._fc_pair(
            F, prefix, inputs, states[0],
            (i2h_weight, h2h_weight, i2h_bias, h2h_bias),
            self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation,
                              name=prefix + "out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (Hochreiter & Schmidhuber 1997); gate order i, f, g, o
    matches the fused RNN op (ops/nn.py _lstm_cell)."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None,
                 activation="tanh", recurrent_activation="sigmoid"):
        super(LSTMCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._declare_gate_params(
            hidden_size, input_size, 4,
            (i2h_weight_initializer, h2h_weight_initializer,
             i2h_bias_initializer, h2h_bias_initializer))
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return self._nc_state_info(batch_size, 2)

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h, h2h = self._fc_pair(
            F, prefix, inputs, states[0],
            (i2h_weight, h2h_weight, i2h_bias, h2h_bias),
            4 * self._hidden_size)
        gate = F.SliceChannel(i2h + h2h, num_outputs=4,
                              name=prefix + "slice")
        act, ract = self._activation, self._recurrent_activation
        in_gate = F.Activation(gate[0], act_type=ract)
        forget_gate = F.Activation(gate[1], act_type=ract)
        in_transform = F.Activation(gate[2], act_type=act)
        out_gate = F.Activation(gate[3], act_type=ract)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type=act)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (Cho et al. 2014), gate order r, z, n as the fused op."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(GRUCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._declare_gate_params(
            hidden_size, input_size, 3,
            (i2h_weight_initializer, h2h_weight_initializer,
             i2h_bias_initializer, h2h_bias_initializer))

    def state_info(self, batch_size=0):
        return self._nc_state_info(batch_size, 1)

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        prev = states[0]
        i2h, h2h = self._fc_pair(
            F, prefix, inputs, prev,
            (i2h_weight, h2h_weight, i2h_bias, h2h_bias),
            3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3,
                                             name=prefix + "i2h_slice")
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3,
                                             name=prefix + "h2h_slice")
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        cand = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1. - update) * cand + update * prev
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stacks multiple cells."""

    def __init__(self, prefix=None, params=None):
        super(SequentialRNNCell, self).__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _gathered_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _gathered_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        cells = list(self._children.values())
        assert not any(isinstance(c, BidirectionalCell) for c in cells)
        return _step_through(cells, inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        cells = list(self._children.values())
        _, _, F, batch_size = _format_sequence(length, inputs, layout,
                                               None)
        begin_state = self._get_begin_state(F, begin_state, inputs,
                                            batch_size)
        cursor, next_states = 0, []
        for i, cell in enumerate(cells):
            width = len(cell.state_info())
            inputs, states = cell.unroll(
                length, inputs=inputs,
                begin_state=begin_state[cursor:cursor + width],
                layout=layout,
                merge_outputs=merge_outputs if i == len(cells) - 1
                else None,
                valid_length=valid_length)
            cursor += width
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError()


class HybridSequentialRNNCell(HybridRecurrentCell):
    """Hybridizable sequential stack."""

    def __init__(self, prefix=None, params=None):
        super(HybridSequentialRNNCell, self).__init__(prefix=prefix,
                                                      params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _gathered_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _gathered_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        return _step_through(list(self._children.values()), inputs,
                             states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        return SequentialRNNCell.unroll(
            self, length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input (a no-state cell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super(DropoutCell, self).__init__(prefix, params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(data=inputs, p=self._rate, axes=self._axes,
                               name="t%d_fwd" % self._counter)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that wrap another cell."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super(ModifierCell, self).__init__(prefix=base_cell.prefix + self._alias(),
                                           params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout (Krueger et al. 2016): randomly keep previous states."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Apply ZoneoutCell to the cells underneath instead."
        self._alias_cache = "zoneout"
        super(ZoneoutCell, self).__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super(ZoneoutCell, self).reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: F.Dropout(F.ones_like(like), p=p)
                if p > 0 else None)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        m_out = mask(p_outputs, next_output)
        output = (F.where(m_out, next_output, prev_output)
                  if m_out is not None else next_output)
        states = ([F.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0. else next_states)
        self._prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the cell output."""

    def __init__(self, base_cell):
        super(ResidualCell, self).__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        merge_outputs = (isinstance(outputs, _get_F(outputs).__dict__.get(
            "NDArray", type(None))) if merge_outputs is None
            else merge_outputs)
        inputs, axis, F, _ = _format_sequence(length, inputs, layout,
                                              merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [o + i for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs two cells over the sequence in opposite directions and
    concatenates their per-step outputs. unroll-only."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super(BidirectionalCell, self).__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _gathered_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _gathered_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs,
                                                       layout, False)
        if valid_length is None:
            reversed_inputs = list(reversed(inputs))
        else:
            # reverse only the valid prefix of each sample so the reverse
            # cell never sees padding before real tokens (reference
            # rnn_cell.py _reverse_sequences / SequenceReverse)
            stacked = F.SequenceReverse(F.stack(*inputs, axis=0),
                                        sequence_length=valid_length,
                                        use_sequence_length=True, axis=0)
            reversed_inputs = list(F.split(stacked, num_outputs=length,
                                           axis=0, squeeze_axis=True)) \
                if length > 1 else [stacked[0]]
        begin_state = self._get_begin_state(F, begin_state, inputs,
                                            batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_outputs = list(reversed(r_outputs))
        else:
            stacked = F.SequenceReverse(F.stack(*r_outputs, axis=0),
                                        sequence_length=valid_length,
                                        use_sequence_length=True, axis=0)
            r_outputs = list(F.split(stacked, num_outputs=length, axis=0,
                                     squeeze_axis=True)) \
                if length > 1 else [stacked[0]]
        outputs = [F.concat(l_o, r_o, dim=1,
                            name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(zip(l_outputs, r_outputs))]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
