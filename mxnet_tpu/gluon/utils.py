"""Gluon utility functions.

Reference: python/mxnet/gluon/utils.py (split_data, split_and_load,
clip_global_norm, check_sha1, download helpers).

TPU note: split_and_load's multi-context copy semantics become sharding —
with a device mesh active, the batch is placed as ONE global array sharded
over the 'dp' axis instead of N per-device copies; the single-element list
return keeps call sites (`for x in split_and_load(...)`) working.
"""

import os
import hashlib

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Splits an NDArray into `num_slice` slices along `batch_axis`
    (gluon/utils.py:34)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data."
            % (str(data.shape), num_slice, batch_axis, num_slice))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Splits an NDArray into len(ctx_list) slices and loads each onto one
    context (gluon/utils.py:85). With a single (TPU) context this is the
    identity; sharded global placement is handled by parallel.shard."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescales NDArrays so that the sum of their 2-norm is smaller than
    max_norm (gluon/utils.py:132)."""
    def _norm(array):
        x = array.reshape((-1,))
        return nd.dot(x, x)
    assert len(arrays) > 0
    total_norm = nd.add_n(*[_norm(arr) for arr in arrays])
    total_norm = nd.sqrt(total_norm)
    total_norm = float(total_norm.asscalar())
    if check_isfinite and not np.isfinite(total_norm):
        import warnings
        warnings.warn(
            UserWarning("nan or inf is detected. Clipping results will be "
                        "undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._data = arr._data * scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """Checks whether the sha1 hash of the file content matches
    (gluon/utils.py:180)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Download a file from a URL (gluon/utils.py:202). This build runs with
    zero egress; only file:// URLs and existing local paths are supported —
    network fetch raises."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[7:], fname)
        return fname
    raise MXNetError(
        "download('%s'): no network egress in this environment; place the "
        "file at '%s' manually" % (url, fname))


def shape_is_known(shape):
    """True when no dimension is unknown (reference gluon/utils.py —
    0/-1 mark deferred dims)."""
    if shape is None:
        return False
    unknown = (-1, 0, None)
    return all(s not in unknown for s in shape)
