"""Gluon DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:77-285 — worker
processes decode/augment and ship batches through POSIX-shm pickled
NDArrays (src/storage/cpu_shared_storage_manager.h:269).

TPU-native design, two tiers:

* ``num_workers>0, thread_pool=True`` — thread pool. Decode/augment is
  numpy/cv2-side and releases the GIL; cheapest when the per-sample work
  is native.
* ``num_workers>0`` (default) — PROCESS pool with shared-memory batch
  passing, the reference's architecture. Each worker runs
  ``dataset[i]`` + a numpy-level batchify and writes the batch into one
  ``multiprocessing.shared_memory`` segment; the parent maps it
  zero-copy and converts to NDArray (the only device transfer).
  Workers NEVER touch jax: the runtime is not fork-safe, so all
  device work stays in the parent (divergence from the reference, where
  workers build shm NDArrays directly — here the NDArray conversion is
  the parent's single cheap step).

Worker start method: ``spawn`` (divergence from the reference's fork:
the parent holds a live multi-threaded jax runtime, which is not
fork-safe). Workers boot clean CPU-pinned interpreters; the dataset and
batchify must be picklable (NDArray implements __reduce__). Set
``MXNET_MP_START_METHOD=fork`` for jax-free parents that need instant
worker startup, or ``thread_pool=True`` for unpicklable datasets.
"""

import os
import pickle
import sys
import traceback
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ... import ndarray as nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch NDArray (recursively for tuples)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


def default_mp_batchify_fn(data):
    """Worker-side batchify: numpy only (jax is not fork-safe; the
    parent converts to NDArray after the shm hop). Reference counterpart:
    default_mp_batchify_fn building shared-mem NDArrays
    (gluon/data/dataloader.py:77)."""
    first = data[0]
    if isinstance(first, nd.NDArray):  # dataset already made NDArrays
        return np.stack([d.asnumpy() for d in data])
    if isinstance(first, (tuple, list)):
        return [default_mp_batchify_fn(list(i)) for i in zip(*data)]
    return np.stack([np.asarray(d) for d in data])


# ------------------------------------------------------ shm transport ---
def _dtype_token(dtype):
    """Round-trippable dtype spelling. `.str` turns ml_dtypes bfloat16
    into an opaque '<V2' void dtype; names survive."""
    name = dtype.name if dtype.names is None else dtype.str
    return name


def _dtype_from_token(token):
    try:
        return np.dtype(token)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, token))


def _tree_arrays(tree, out):
    """Flatten nested lists/tuples of ndarrays, collecting leaves."""
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_arrays(t, out) for t in tree)
    arr = np.ascontiguousarray(np.asarray(tree))
    out.append(arr)
    return len(out) - 1  # leaf placeholder: index into the array list


def _tree_fill(tree, leaves):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_fill(t, leaves) for t in tree)
    return leaves[tree]


def _batch_to_shm(batch):
    """Write every array leaf of `batch` into ONE SharedMemory segment.
    Returns (shm_name, structure, specs) — specs are (offset, shape,
    dtype_str) per leaf. The worker closes its mapping but does NOT
    unlink; the consumer unlinks after mapping (see _batch_from_shm)."""
    from multiprocessing import shared_memory
    arrays = []
    structure = _tree_arrays(batch, arrays)
    total = sum(a.nbytes for a in arrays)
    shm = shared_memory.SharedMemory(create=True, size=max(total, 1))
    specs = []
    off = 0
    for a in arrays:
        view = np.ndarray(a.shape, a.dtype, buffer=shm.buf, offset=off)
        view[...] = a
        specs.append((off, a.shape, _dtype_token(a.dtype)))
        off += a.nbytes
    name = shm.name
    shm.close()
    return name, structure, specs


def _batch_from_shm(name, structure, specs, convert):
    """Map the segment, rebuild the batch tree, unlink. The numpy views
    keep the mapping alive via the shm buffer; `convert` turns each leaf
    into its final form (NDArray in the parent) BEFORE the local handle
    is dropped, so no view outlives the segment."""
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(name=name)
    try:
        leaves = [convert(np.ndarray(shape, _dtype_from_token(dt),
                                     buffer=shm.buf, offset=off))
                  for off, shape, dt in specs]
        return _tree_fill(structure, leaves)
    finally:
        try:
            shm.close()
        finally:
            try:
                shm.unlink()
            except FileNotFoundError:
                pass


def _spawn_worker_entry(payload, key_queue, data_queue):
    """Spawn-mode entry: pin the CPU platform BEFORE unpickling anything
    (unpickling NDArrays re-creates them through jax — the worker must
    never initialize the parent's accelerator plugin, and several
    workers grabbing one TPU chip would wedge it)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        dataset, batchify_fn = pickle.loads(payload)
    except Exception:
        # a worker that cannot even build its dataset must say so, or
        # the parent would block forever on an empty data queue
        data_queue.put((-1, -1, "fatal", traceback.format_exc()))
        os._exit(1)
    _worker_loop(dataset, key_queue, data_queue, batchify_fn)


def _worker_loop(dataset, key_queue, data_queue, batchify_fn):
    """Worker process body — PERSISTENT across epochs (spawn startup is
    seconds; the reference likewise keeps its worker pool alive for the
    DataLoader's lifetime). Batches go out through shm; only
    (generation, index, shm-spec) crosses the queue."""
    os.environ["JAX_PLATFORMS"] = "cpu"  # if anything strays into jax
    while True:
        item = key_queue.get()
        if item is None:
            break
        gen, idx, indices = item
        try:
            batch = batchify_fn([dataset[i] for i in indices])
            payload = _batch_to_shm(batch)
            data_queue.put((gen, idx, "ok", payload))
        except Exception:
            data_queue.put((gen, idx, "error", traceback.format_exc()))
    # skip atexit: a forked child inherits jax/XLA state whose teardown
    # hooks can hang without the parent's threads
    data_queue.close()
    data_queue.join_thread()
    os._exit(0)


def _shutdown_pool(key_queue, data_queue, workers):
    """Finalizer for the persistent pool (module-level: must not retain
    the DataLoader). Sends one sentinel per worker, then reaps."""
    try:
        for _ in workers:
            key_queue.put(None)
    except Exception:
        pass
    # drain so worker feeder threads can flush and exit, and so
    # outstanding shm segments get unlinked
    try:
        while True:
            rgen, idx, status, payload = data_queue.get(timeout=0.2)
            if status == "ok":
                _batch_from_shm(*payload, convert=lambda a: None)
    except Exception:
        pass
    for w in workers:
        w.join(timeout=5)
        if w.is_alive():
            w.terminate()


class DataLoader(object):
    """Loads data from a Dataset and returns mini-batches.

    Parameters mirror the reference loader: dataset, batch_size, shuffle,
    sampler, last_batch, batch_sampler, batchify_fn, num_workers,
    pin_memory (accepted, no-op on TPU), prefetch, thread_pool.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._user_batchify = batchify_fn
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    # ------------------------------------------------------ iteration ---
    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._make_batch(batch)
            return
        if self._thread_pool:
            yield from self._iter_threads()
        else:
            yield from self._iter_processes()

    def _iter_threads(self):
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._num_workers + self._prefetch):
                    futures.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                it = None
            while futures:
                batch = futures.pop(0).result()
                if it is not None:
                    try:
                        futures.append(pool.submit(self._make_batch,
                                                   next(it)))
                    except StopIteration:
                        it = None
                yield batch

    def _ensure_pool(self):
        """Start (once) the persistent worker pool; respawning per epoch
        would pay seconds of spawn startup every __iter__."""
        if getattr(self, "_pool_workers", None):
            return
        import multiprocessing as mp
        import weakref
        # default SPAWN, not the reference's fork: the parent holds a
        # live multi-threaded jax runtime, and forking it deadlocks
        # probabilistically (a forked child inherits whatever locks
        # other threads held). Spawned workers boot clean interpreters
        # pinned to the CPU platform. MXNET_MP_START_METHOD=fork remains
        # available for jax-free parents that need instant startup.
        method = os.environ.get("MXNET_MP_START_METHOD", "spawn")
        ctx = mp.get_context(method)
        batchify = self._user_batchify if self._user_batchify is not None \
            else default_mp_batchify_fn
        self._key_queue = ctx.Queue()
        self._data_queue = ctx.Queue()
        if method == "fork":
            workers = [ctx.Process(
                target=_worker_loop,
                args=(self._dataset, self._key_queue, self._data_queue,
                      batchify), daemon=True)
                for _ in range(self._num_workers)]
        else:
            payload = pickle.dumps((self._dataset, batchify))
            workers = [ctx.Process(
                target=_spawn_worker_entry,
                args=(payload, self._key_queue, self._data_queue),
                daemon=True) for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        self._pool_workers = workers
        self._pool_gen = 0
        # shut the pool down when the loader is garbage collected, via a
        # finalizer that must NOT hold a reference back to self
        weakref.finalize(self, _shutdown_pool, self._key_queue,
                         self._data_queue, workers)

    def _get_result(self, data_queue):
        """data_queue.get with worker-liveness checks: a dead pool must
        raise, not hang the parent forever."""
        import queue as _queue
        from ...base import MXNetError
        while True:
            try:
                return data_queue.get(timeout=5)
            except _queue.Empty:
                dead = [w.pid for w in self._pool_workers
                        if w.exitcode is not None]
                if dead:
                    raise MXNetError(
                        "DataLoader worker process(es) %s died without "
                        "reporting a result (killed? failed to start?); "
                        "aborting iteration" % dead)

    def _iter_processes(self):
        from ...base import MXNetError
        if getattr(self, "_iter_active", False):
            # one persistent pool, shared queues: two interleaved epochs
            # would consume each other's results. Fail loudly (the
            # reference's per-iterator worker sets allow this; here use
            # separate DataLoaders or thread_pool=True instead).
            raise MXNetError(
                "concurrent iteration of a multiprocess DataLoader is "
                "not supported; create separate DataLoader objects or "
                "use thread_pool=True")
        self._iter_active = True
        self._ensure_pool()
        self._pool_gen += 1
        gen = self._pool_gen
        key_queue, data_queue = self._key_queue, self._data_queue

        def to_nd(arr):
            # the parent's one device hop. The copy is REQUIRED: jax's
            # CPU backend aliases host numpy buffers zero-copy, so an
            # NDArray built directly on the shm view would dangle once
            # the segment is unlinked (observed as a segfault).
            return nd.array(np.array(arr, copy=True))

        sent = 0
        received = {}
        next_idx = 0
        it = iter(self._batch_sampler)
        try:
            for _ in range(self._num_workers + self._prefetch):
                try:
                    key_queue.put((gen, sent, next(it)))
                    sent += 1
                except StopIteration:
                    it = None
                    break
            while next_idx < sent:
                while next_idx not in received:
                    rgen, idx, status, payload = self._get_result(
                        data_queue)
                    if status == "fatal":
                        raise MXNetError(
                            "DataLoader worker failed to start:\n%s"
                            % payload)
                    if rgen != gen:   # stale epoch (early break): drop
                        if status == "ok":
                            _batch_from_shm(*payload,
                                            convert=lambda a: None)
                        continue
                    if status == "error":
                        raise MXNetError(
                            "DataLoader worker failed:\n%s" % payload)
                    received[idx] = payload
                payload = received.pop(next_idx)
                next_idx += 1
                if it is not None:
                    try:
                        key_queue.put((gen, sent, next(it)))
                        sent += 1
                    except StopIteration:
                        it = None
                yield _batch_from_shm(*payload, convert=to_nd)
        finally:
            # results of this epoch that were never consumed (early
            # break) stay queued; the NEXT epoch's stale-generation
            # check unlinks them lazily. The pool outlives the epoch.
            self._iter_active = False
            for payload in received.values():
                _batch_from_shm(*payload, convert=lambda a: None)

    def __len__(self):
        return len(self._batch_sampler)
