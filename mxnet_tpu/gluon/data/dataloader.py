"""Gluon DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:77-285 — there, worker
processes decode/augment and ship batches through POSIX-shm pickled
NDArrays. TPU-native divergence: JAX runtimes are not fork-safe, so
`num_workers>0` uses a THREAD pool (decode/augment is numpy-side and
releases the GIL in practice); batches land on device asynchronously via
the normal dispatch queue. The shared-memory IPC layer is unnecessary —
device transfer is the only copy.
"""

import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ... import ndarray as nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch NDArray (recursively for tuples)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader(object):
    """Loads data from a Dataset and returns mini-batches.

    Parameters mirror the reference loader: dataset, batch_size, shuffle,
    sampler, last_batch, batch_sampler, batchify_fn, num_workers,
    pin_memory (accepted, no-op on TPU), prefetch.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._make_batch(batch)
            return

        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._num_workers + self._prefetch):
                    futures.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                it = None
            while futures:
                batch = futures.pop(0).result()
                if it is not None:
                    try:
                        futures.append(pool.submit(self._make_batch,
                                                   next(it)))
                    except StopIteration:
                        it = None
                yield batch

    def __len__(self):
        return len(self._batch_sampler)
