"""Gluon vision datasets.

Reference: python/mxnet/gluon/data/vision/datasets.py (MNIST,
FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset, ImageFolderDataset).
Divergence: this environment has no network egress, so datasets read
from `root` only (same on-disk formats as the reference: MNIST
idx-ubyte, CIFAR binary batches) and raise a clear error when absent
instead of downloading.
"""

import gzip
import os
import struct
import warnings

import numpy as np

from .... import ndarray as nd
from .... import image as _image_mod
from ..dataset import Dataset, ArrayDataset, RecordFileDataset
from ... import utils as _gutils

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        super(_DownloadedDataset, self).__init__()
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError()


def _open_maybe_gz(path):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise IOError(
        "%s not found. This build has no network egress — place the "
        "dataset files under the dataset root yourself." % path)


class MNIST(_DownloadedDataset):
    """MNIST from idx-ubyte files under `root` (no auto-download)."""

    _files = {True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super(MNIST, self).__init__(root, transform)

    def _get_data(self):
        image_file, label_file = (os.path.join(self._root, f)
                                  for f in self._files[self._train])
        with _open_maybe_gz(label_file) as fin:
            magic, num = struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(num), dtype=np.uint8) \
                .astype(np.int32)
        with _open_maybe_gz(image_file) as fin:
            magic, num, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(num * rows * cols),
                                 dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class FashionMNIST(MNIST):
    """FashionMNIST — same idx format as MNIST, different root."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super(FashionMNIST, self).__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the binary batch files under `root`."""

    _train_files = ["data_batch_%d.bin" % i for i in range(1, 6)]
    _test_files = ["test_batch.bin"]
    _rec_len = 3073

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        self._train = train
        super(CIFAR10, self).__init__(root, transform)

    def _read_batch(self, filename):
        with _open_maybe_gz(filename) as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        data = raw.reshape(-1, self._rec_len)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        data, label = zip(*[self._read_batch(os.path.join(self._root, f))
                            for f in files])
        self._data = nd.array(np.concatenate(data), dtype="uint8")
        self._label = np.concatenate(label)


class CIFAR100(CIFAR10):
    """CIFAR100 binary format; fine_label selects the 100-class label."""

    _train_files = ["train.bin"]
    _test_files = ["test.bin"]
    _rec_len = 3074

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super(CIFAR100, self).__init__(root, train, transform)

    def _read_batch(self, filename):
        with _open_maybe_gz(filename) as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        data = raw.reshape(-1, self._rec_len)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 + self._fine_label].astype(np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO file (recordio.pack_img)."""

    def __init__(self, filename, flag=1, transform=None):
        super(ImageRecordDataset, self).__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        record = super(ImageRecordDataset, self).__getitem__(idx)
        header, img = recordio.unpack(record)
        img = _image_mod.imdecode(img, self._flag)
        if self._transform is not None:
            return self._transform(img, header.label)
        return img, header.label


class ImageFolderDataset(Dataset):
    """root/category/image.ext layout; label = sorted folder index."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                warnings.warn("Ignoring %s, which is not a directory." % path,
                              stacklevel=3)
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    warnings.warn(
                        "Ignoring %s of type %s. Only support %s" % (
                            filename, ext, ", ".join(self._exts)))
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        filename, label = self.items[idx]
        if filename.endswith(".npy"):
            img = nd.array(np.load(filename))
        else:
            img = _image_mod.imread(filename, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
