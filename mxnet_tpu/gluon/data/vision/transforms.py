"""Gluon vision transforms.

Reference: python/mxnet/gluon/data/vision/transforms.py. Host-side
transforms (decode/resize/crop) run in numpy/cv2; pure-math transforms
(ToTensor/Normalize/flip) are Blocks over nd ops so they can also fuse
into a jit graph.
"""

import random as pyrandom

import numpy as np

from .... import ndarray as nd
from .... import image as _image
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CropResize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting", "RandomGray"]


class Compose(Sequential):
    """Sequentially composes transforms."""

    def __init__(self, transforms):
        super(Compose, self).__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super(Cast, self).__init__()
        self._dtype = dtype

    def forward(self, x):
        # numpy passthrough keeps DataLoader worker pipelines jax-free
        if isinstance(x, np.ndarray):
            return x.astype(self._dtype)
        return super(Cast, self).forward(x)

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] via the _image_to_tensor op
    (handles NHWC batches too; rank is resolved at trace time)."""

    def forward(self, x):
        # numpy passthrough: per-image eager jax ops dominate process-
        # pool DataLoader workers (benchmark/input_pipeline_bench.py)
        if isinstance(x, np.ndarray):
            out = x.astype(np.float32) / 255.0
            axes = (2, 0, 1) if out.ndim == 3 else (0, 3, 1, 2)
            return out.transpose(axes)
        return super(ToTensor, self).forward(x)

    def hybrid_forward(self, F, x):
        return F.image.to_tensor(x)


class Normalize(HybridBlock):
    """(x - mean) / std on CHW (or NCHW) float input via _image_normalize
    — an op available in both nd and sym namespaces, so hybridize works."""

    def __init__(self, mean=0.0, std=1.0):
        super(Normalize, self).__init__()
        self._mean = tuple(np.atleast_1d(np.asarray(mean, np.float32)))
        self._std = tuple(np.atleast_1d(np.asarray(std, np.float32)))
        # per-image hot loop constants (numpy passthrough path)
        self._mean_np = np.asarray(self._mean, np.float32).reshape(-1, 1, 1)
        self._std_np = np.asarray(self._std, np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        if isinstance(x, np.ndarray):  # CHW or NCHW float
            return (x.astype(np.float32) - self._mean_np) / self._std_np
        return super(Normalize, self).forward(x)

    def hybrid_forward(self, F, x):
        return F.image.normalize(x, mean=self._mean, std=self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super(Resize, self).__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        if isinstance(self._size, int):
            if self._keep:
                return _image.resize_short(x, self._size, self._interp)
            return _image.imresize(x, self._size, self._size, self._interp)
        return _image.imresize(x, self._size[0], self._size[1],
                               self._interp)


class CropResize(Block):
    """Fixed-window crop at (x, y, w, h), optionally resized to `size`
    (reference transforms.py CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super(CropResize, self).__init__()
        self._x = x
        self._y = y
        self._w = width
        self._h = height
        self._size = size
        self._interp = interpolation

    def forward(self, x):
        size = None
        if self._size is not None:
            size = (self._size, self._size) \
                if isinstance(self._size, int) else tuple(self._size)
        return _image.fixed_crop(x, self._x, self._y, self._w, self._h,
                                 size, self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super(CenterCrop, self).__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        return _image.center_crop(x, self._size, self._interp)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super(RandomResizedCrop, self).__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        return _image.random_size_crop(x, self._size, self._scale,
                                       self._ratio, self._interp)[0]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if pyrandom.random() < 0.5:
            arr = _image._as_np(x)[:, ::-1]
            return _image._like(x, np.ascontiguousarray(arr))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if pyrandom.random() < 0.5:
            arr = _image._as_np(x)[::-1]
            return _image._like(x, np.ascontiguousarray(arr))
        return x


class _JitterBlock(Block):
    aug_cls = None

    def __init__(self, amount):
        super(_JitterBlock, self).__init__()
        self._aug = self.aug_cls(amount)

    def forward(self, x):
        return self._aug(x)


class RandomBrightness(_JitterBlock):
    aug_cls = _image.BrightnessJitterAug


class RandomContrast(_JitterBlock):
    aug_cls = _image.ContrastJitterAug


class RandomSaturation(_JitterBlock):
    aug_cls = _image.SaturationJitterAug


class RandomHue(_JitterBlock):
    aug_cls = _image.HueJitterAug


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super(RandomColorJitter, self).__init__()
        self._aug = _image.ColorJitterAug(brightness, contrast, saturation)
        self._hue = _image.HueJitterAug(hue) if hue else None

    def forward(self, x):
        x = self._aug(x)
        if self._hue:
            x = self._hue(x)
        return x


class RandomLighting(Block):
    def __init__(self, alpha):
        super(RandomLighting, self).__init__()
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        self._aug = _image.LightingAug(alpha, eigval, eigvec)

    def forward(self, x):
        return self._aug(x)


class RandomGray(Block):
    def __init__(self, p=0.5):
        super(RandomGray, self).__init__()
        self._aug = _image.RandomGrayAug(p)

    def forward(self, x):
        return self._aug(x)
