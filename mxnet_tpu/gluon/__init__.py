"""Gluon — the imperative / hybrid frontend.

Reference: python/mxnet/gluon/ (Block/HybridBlock in block.py, Parameter in
parameter.py, Trainer, losses, nn/rnn layers, data pipeline, model_zoo).
"""

from . import parameter
from .parameter import Parameter, Constant, ParameterDict

from . import block
from .block import Block, HybridBlock, SymbolBlock

from . import trainer
from .trainer import Trainer

from . import utils
from .utils import split_data, split_and_load, clip_global_norm

from . import nn
from . import loss
from . import rnn
from . import data
from . import model_zoo
from . import contrib
