"""Gluon activation layers.

Reference: python/mxnet/gluon/nn/activations.py (Activation, LeakyReLU,
PReLU, ELU, SELU, Swish; GELU added in contrib). All map to single XLA
elementwise ops which fuse into adjacent matmuls/convs.
"""

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish",
           "GELU"]


class Activation(HybridBlock):
    """Applies an activation function: 'relu', 'sigmoid', 'tanh',
    'softrelu', 'softsign' (gluon/nn/activations.py:30)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super(Activation, self).__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type, name="fwd")

    def __repr__(self):
        return "{name}({act})".format(name=self.__class__.__name__,
                                      act=self._act_type)


class LeakyReLU(HybridBlock):
    """Leaky ReLU: f(x) = x if x > 0 else alpha*x."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be >= 0."
        super(LeakyReLU, self).__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha, name="fwd")

    def __repr__(self):
        return "{name}({alpha})".format(name=self.__class__.__name__,
                                        alpha=self._alpha)


class PReLU(HybridBlock):
    """Parametric leaky ReLU with learned slope (gluon/nn/activations.py:86)."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super(PReLU, self).__init__(**kwargs)
        from ... import initializer
        if alpha_initializer is None:
            alpha_initializer = initializer.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu", name="fwd")


class ELU(HybridBlock):
    """f(x) = x if x > 0 else alpha*(exp(x)-1)."""

    def __init__(self, alpha=1.0, **kwargs):
        super(ELU, self).__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled exponential linear unit (Klambauer et al. 2017)."""

    def __init__(self, **kwargs):
        super(SELU, self).__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu", name="fwd")


class Swish(HybridBlock):
    """x * sigmoid(beta * x) (Ramachandran et al. 2017)."""

    def __init__(self, beta=1.0, **kwargs):
        super(Swish, self).__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Gaussian error linear unit: x * Phi(x)
    (gluon/nn/activations.py GELU via LeakyReLU act_type='gelu')."""

    def __init__(self, **kwargs):
        super(GELU, self).__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu", name="fwd")
