"""Gluon convolutional and pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py:47-1202 (Conv1D/2D/3D,
Conv*DTranspose, Max/Avg/GlobalMax/GlobalAvg pooling, ReflectionPad2D).

TPU notes: convs lower to lax.conv_general_dilated on the MXU (NC[DHW]
layout kept for API parity; XLA re-layouts internally); max pooling
lowers to native lax.reduce_window (ops/nn.py:pooling), avg/sum/lp to
a fused strided-slice window accumulation (ops/nn.py:_window_reduce).
"""

from ..block import HybridBlock
from .activations import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D",
           "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _to_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _sized(value, ndim, label="kernel_size"):
    """Broadcast an int to an ndim-tuple and validate the arity."""
    out = _to_tuple(value, ndim)
    assert len(out) == ndim, \
        "%s must be a number or %d-tuple" % (label, ndim)
    return out


class _Conv(HybridBlock):
    """Base convolution (gluon/nn/conv_layers.py:47)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super(_Conv, self).__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            ndim = len(kernel_size)
            strides = _to_tuple(strides, ndim)
            padding = _to_tuple(padding, ndim)
            dilation = _to_tuple(dilation, ndim)
            self._op_name = op_name
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "dilate": dilation,
                "pad": padding, "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias, "layout": layout}
            if adj is not None:
                self._kwargs["adj"] = adj

            if op_name == "Convolution":
                wshape = (channels, in_channels // groups) + \
                    tuple(kernel_size) if in_channels else \
                    (channels, 0) + tuple(kernel_size)
            else:  # Deconvolution: weight is (in, out//groups, *k)
                wshape = (in_channels, channels // groups) + \
                    tuple(kernel_size) if in_channels else \
                    (0, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, name="fwd", **self._kwargs)
        else:
            act = op(x, weight, bias, name="fwd", **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def _alias(self):
        return "conv"

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if hasattr(self, "out_pad") and self.out_pad != (0,) * len_kernel_size:
            s += ", output_padding={out_pad}".format(out_pad=self.out_pad)
        if self._kwargs["num_group"] != 1:
            s += ", groups={num_group}"
        if self.bias is None:
            s += ", bias=False"
        if self.act:
            s += ", {}".format(self.act)
        s += ")"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(
                            shape[1] if shape[1] else None, shape[0]),
                        **self._kwargs)


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super(Conv1D, self).__init__(
            channels, _sized(kernel_size, 1), strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv2D, self).__init__(
            channels, _sized(kernel_size, 2), strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super(Conv3D, self).__init__(
            channels, _sized(kernel_size, 3), strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _sized(kernel_size, 1)
        output_padding = _sized(output_padding, 1, "output_padding")
        super(Conv1DTranspose, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution", adj=output_padding,
            **kwargs)
        self.outpad = output_padding


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _sized(kernel_size, 2)
        output_padding = _sized(output_padding, 2, "output_padding")
        super(Conv2DTranspose, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution", adj=output_padding,
            **kwargs)
        self.outpad = output_padding


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _sized(kernel_size, 3)
        output_padding = _sized(output_padding, 3, "output_padding")
        super(Conv3DTranspose, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution", adj=output_padding,
            **kwargs)
        self.outpad = output_padding


class _Pooling(HybridBlock):
    """Base pooling (gluon/nn/conv_layers.py:671)."""

    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout=None,
                 count_include_pad=None, **kwargs):
        super(_Pooling, self).__init__(**kwargs)
        if strides is None:
            strides = pool_size
        if isinstance(strides, int):
            strides = (strides,) * len(pool_size)
        if isinstance(padding, int):
            padding = (padding,) * len(pool_size)
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, name="fwd", **self._kwargs)

    def __repr__(self):
        s = "{name}(size={kernel}, stride={stride}, padding={pad}" \
            ", ceil_mode={ceil_mode}, global_pool={global_pool}, pool_type={pool_type})"
        return s.format(
            name=self.__class__.__name__,
            ceil_mode=self._kwargs["pooling_convention"] == "full",
            **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        super(MaxPool1D, self).__init__(
            _sized(pool_size, 1, "pool_size"), strides, padding, ceil_mode, False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        assert layout == "NCHW", "Only NCHW layout is supported"
        super(MaxPool2D, self).__init__(
            _sized(pool_size, 2, "pool_size"), strides, padding, ceil_mode, False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        super(MaxPool3D, self).__init__(
            _sized(pool_size, 3, "pool_size"), strides, padding, ceil_mode, False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        super(AvgPool1D, self).__init__(
            _sized(pool_size, 1, "pool_size"), strides, padding, ceil_mode, False, "avg", layout,
            count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        assert layout == "NCHW", "Only NCHW layout is supported"
        super(AvgPool2D, self).__init__(
            _sized(pool_size, 2, "pool_size"), strides, padding, ceil_mode, False, "avg", layout,
            count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        super(AvgPool3D, self).__init__(
            _sized(pool_size, 3, "pool_size"), strides, padding, ceil_mode, False, "avg", layout,
            count_include_pad, **kwargs)


def _global_pool(name, ndim, pool_type, want_layout):
    """Build a Global{Max,Avg}Pool{1,2,3}D class: full-spatial pooling
    is one flag on _Pooling, so the six variants differ only in their
    (ndim, type, layout) triple."""
    def __init__(self, layout=want_layout, **kwargs):
        assert layout == want_layout, \
            "Only %s layout is supported" % want_layout
        _Pooling.__init__(self, (1,) * ndim, None, 0, True, True,
                          pool_type, **kwargs)
    cls = type(name, (_Pooling,), {"__init__": __init__})
    cls.__doc__ = "Global %s pooling over %dD spatial dims " \
                  "(gluon/nn/conv_layers.py Global*Pool)." \
                  % (pool_type, ndim)
    return cls


GlobalMaxPool1D = _global_pool("GlobalMaxPool1D", 1, "max", "NCW")
GlobalMaxPool2D = _global_pool("GlobalMaxPool2D", 2, "max", "NCHW")
GlobalMaxPool3D = _global_pool("GlobalMaxPool3D", 3, "max", "NCDHW")
GlobalAvgPool1D = _global_pool("GlobalAvgPool1D", 1, "avg", "NCW")
GlobalAvgPool2D = _global_pool("GlobalAvgPool2D", 2, "avg", "NCHW")
GlobalAvgPool3D = _global_pool("GlobalAvgPool3D", 3, "avg", "NCDHW")


class ReflectionPad2D(HybridBlock):
    """Pads the input with the reflection of the boundary
    (gluon/nn/conv_layers.py:1151)."""

    def __init__(self, padding=0, **kwargs):
        super(ReflectionPad2D, self).__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        assert len(padding) == 8
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
