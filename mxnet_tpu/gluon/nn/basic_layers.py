"""Gluon basic layers.

Reference: python/mxnet/gluon/nn/basic_layers.py:34-758 (Sequential,
HybridSequential, Dense, Dropout, Embedding, BatchNorm, InstanceNorm,
LayerNorm, GroupNorm, Flatten, Lambda, HybridLambda).

TPU notes: BatchNorm's running-stat update is expressed functionally — the
op returns batch stats and the layer (eager) or the graph executor
(hybridized, executor.build_graph_fn BatchNorm clause) folds the momentum
update, instead of the reference's in-kernel aux mutation
(src/operator/nn/batch_norm.cc).
"""

from ... import autograd
from ... import initializer as init
from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "Concurrent", "HybridConcurrent", "Identity"]


class _ChainContainer(object):
    """Shared container protocol for the two sequential stacks: add(),
    chained application, indexing (slices clone into a same-prefix
    container), len/iter, and the tree repr — written once instead of
    twice."""

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _apply_chain(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        body = "\n".join(
            "  (%s): %s" % (key, repr(block).replace("\n", "\n  "))
            for key, block in self._children.items())
        return "%s(\n%s\n)" % (type(self).__name__, body)

    def __getitem__(self, key):
        picked = list(self._children.values())[key]
        if not isinstance(picked, list):
            return picked
        clone = type(self)(prefix=self._prefix)
        with clone.name_scope():
            clone.add(*picked)
        return clone

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Sequential(_ChainContainer, Block):
    """Stacks Blocks sequentially (gluon/nn/basic_layers.py:34)."""

    def forward(self, x):
        return self._apply_chain(x)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer '%s' are "
                "HybridBlocks. Consider using HybridSequential for the "
                "best performance." % self.prefix, stacklevel=2)
        super(Sequential, self).hybridize(active, **kwargs)


class HybridSequential(_ChainContainer, HybridBlock):
    """Stacks HybridBlocks sequentially (gluon/nn/basic_layers.py:117)."""

    def hybrid_forward(self, F, x):
        return self._apply_chain(x)


class Dense(HybridBlock):
    """Densely-connected layer: out = act(dot(x, W^T) + b)
    (gluon/nn/basic_layers.py:167). The matmul maps straight onto the MXU."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super(Dense, self).__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten,
                               name="fwd")
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({layout}, {act})"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        act=self.act if self.act else "linear",
                        layout="{0} -> {1}".format(
                            shape[1] if shape[1] else None, shape[0]))


class Dropout(HybridBlock):
    """Dropout regularization (gluon/nn/basic_layers.py:237). Uses the
    counter-based threefry RNG — inside a CachedOp trace the key is a real
    computation input, so compiled dropout stays fresh per step."""

    def __init__(self, rate, axes=(), **kwargs):
        super(Dropout, self).__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd",
                             cudnn_off=False)
        return F._copy(x)

    def __repr__(self):
        s = "{name}(p = {_rate}, axes={_axes})"
        return s.format(name=self.__class__.__name__, **self.__dict__)


def _affine_pair(layer, in_channels, gamma_init, beta_init,
                 scale, center, track_grads=True):
    """Declare the norm family's gamma/beta pair under the layer's
    scope. scale/center toggle learnability (grad_req null keeps the
    param present for checkpoint parity even when frozen)."""
    def declare(name, init, learn):
        kw = dict(grad_req="write" if learn else "null",
                  shape=(in_channels,), init=init,
                  allow_deferred_init=True)
        if track_grads:
            kw["differentiable"] = learn
        return layer.params.get(name, **kw)
    layer.gamma = declare("gamma", gamma_init, scale)
    layer.beta = declare("beta", beta_init, center)


def _norm_repr(layer):
    inside = ", ".join("%s=%r" % kv for kv in layer._kwargs.items())
    width = layer.gamma.shape[0]
    return "%s(%s, in_channels=%s)" % (type(layer).__name__, inside,
                                       width if width else None)


class BatchNorm(HybridBlock):
    """Batch normalization (gluon/nn/basic_layers.py:291)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super(BatchNorm, self).__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        if in_channels != 0:
            self.in_channels = in_channels
        with self.name_scope():
            _affine_pair(self, in_channels, gamma_initializer,
                         beta_initializer, scale, center)
            for stat, init in (("running_mean", running_mean_initializer),
                               ("running_var",
                                running_variance_initializer)):
                setattr(self, stat, self.params.get(
                    stat, grad_req="null", shape=(in_channels,),
                    init=init, allow_deferred_init=True,
                    differentiable=False))

    def cast(self, dtype):
        if dtype in ("float16", "bfloat16"):
            dtype = "float32"  # stats stay fp32 (reference: BN runs fp32)
        super(BatchNorm, self).cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        if F is nd and autograd.is_training() \
                and not self._kwargs["use_global_stats"]:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, name="fwd", **self._kwargs)
            with autograd.pause():
                mom = self._kwargs["momentum"]
                running_mean._data = (mom * running_mean._data +
                                      (1 - mom) * mean._data)
                running_var._data = (mom * running_var._data +
                                     (1 - mom) * var._data)
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           name="fwd", **self._kwargs)

    def __repr__(self):
        return _norm_repr(self)


class Embedding(HybridBlock):
    """Turns non-negative integers into dense vectors
    (gluon/nn/basic_layers.py:397). On TPU this is a one-hot matmul /
    gather chosen by XLA."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super(Embedding, self).__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        s = "{block_name}({input_dim} -> {output_dim}, {dtype})"
        return s.format(block_name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """Flattens the input to (batch, -1) (gluon/nn/basic_layers.py:459)."""

    def __init__(self, **kwargs):
        super(Flatten, self).__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    """Instance normalization (gluon/nn/basic_layers.py:480)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super(InstanceNorm, self).__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            _affine_pair(self, in_channels, gamma_initializer,
                         beta_initializer, scale, center,
                         track_grads=False)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        return _norm_repr(self)


class LayerNorm(HybridBlock):
    """Layer normalization over the last (or given) axis
    (gluon/nn/basic_layers.py:563)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super(LayerNorm, self).__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            _affine_pair(self, in_channels, gamma_initializer,
                         beta_initializer, scale, center,
                         track_grads=False)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.LayerNorm(data, gamma=gamma, beta=beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        return _norm_repr(self)


class GroupNorm(HybridBlock):
    """Group normalization (gluon/nn/basic_layers.py:657)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super(GroupNorm, self).__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "num_groups": num_groups}
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            _affine_pair(self, in_channels, gamma_initializer,
                         beta_initializer, scale, center,
                         track_grads=False)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.GroupNorm(data, gamma=gamma, beta=beta,
                           num_groups=self._num_groups, eps=self._epsilon)

    def __repr__(self):
        inside = ", ".join("%s=%r" % kv for kv in self._kwargs.items())
        return "%s(%s)" % (type(self).__name__, inside)


def _named_callable(function, namespaces):
    """Resolve a Lambda layer's function argument: a name looked up in
    the given op namespaces (returns a {namespace: fn} dispatch map), or
    a callable used as-is. Returns (impl, display_name)."""
    if callable(function):
        return function, function.__name__
    if isinstance(function, str):
        table = {ns: getattr(ns, function, None) for ns in namespaces}
        if any(fn is not None for fn in table.values()):
            return table, function
        raise AssertionError(
            "Function name %s is not found in %s." % (
                function, "/".join(ns.__name__.rsplit(".", 1)[-1]
                                   for ns in namespaces)))
    raise ValueError("Unrecognized function in lambda: {} of type {}"
                     .format(function, type(function)))


class Lambda(Block):
    """Wraps a function as a Block (gluon/nn/basic_layers.py:727)."""

    def __init__(self, function, prefix=None):
        super(Lambda, self).__init__(prefix=prefix)
        impl, self._func_name = _named_callable(function, (nd,))
        self._func_impl = impl[nd] if isinstance(impl, dict) else impl

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._func_name)


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock (gluon/nn/basic_layers.py:758)."""

    def __init__(self, function, prefix=None):
        super(HybridLambda, self).__init__(prefix=prefix)
        from ... import symbol as sym
        self._func, self._func_name = _named_callable(function, (sym, nd))

    def hybrid_forward(self, F, x, *args):
        if isinstance(self._func, dict):
            return self._func[F](x, *args)
        return self._func(F, x, *args)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._func_name)


from .activations import Activation  # noqa: E402  (Dense uses it)


class Concurrent(Sequential):
    """Runs children on the same input and concatenates their outputs
    along `axis` (reference gluon/contrib/nn/basic_layers.py Concurrent;
    promoted into gluon.nn as in later MXNet)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(Concurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Concurrent layer '%s' are HybridBlocks. "
                "Consider using HybridConcurrent for the best performance."
                % self.prefix, stacklevel=2)
        Block.hybridize(self, active, **kwargs)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (one XLA fusion per parallel branch set)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(HybridConcurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping — useful as a no-op branch in Concurrent."""

    def hybrid_forward(self, F, x):
        return x
