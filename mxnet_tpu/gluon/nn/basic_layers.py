"""Gluon basic layers.

Reference: python/mxnet/gluon/nn/basic_layers.py:34-758 (Sequential,
HybridSequential, Dense, Dropout, Embedding, BatchNorm, InstanceNorm,
LayerNorm, GroupNorm, Flatten, Lambda, HybridLambda).

TPU notes: BatchNorm's running-stat update is expressed functionally — the
op returns batch stats and the layer (eager) or the graph executor
(hybridized, executor.build_graph_fn BatchNorm clause) folds the momentum
update, instead of the reference's in-kernel aux mutation
(src/operator/nn/batch_norm.cc).
"""

from ... import autograd
from ... import initializer as init
from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "Concurrent", "HybridConcurrent", "Identity"]


class Sequential(Block):
    """Stacks Blocks sequentially (gluon/nn/basic_layers.py:34)."""

    def __init__(self, prefix=None, params=None):
        super(Sequential, self).__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(key=key, block=repr(block).replace("\n", "\n  "))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer '%s' are HybridBlocks. "
                "Consider using HybridSequential for the best performance."
                % self.prefix, stacklevel=2)
        super(Sequential, self).hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks sequentially (gluon/nn/basic_layers.py:117)."""

    def __init__(self, prefix=None, params=None):
        super(HybridSequential, self).__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(key=key, block=repr(block).replace("\n", "\n  "))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Densely-connected layer: out = act(dot(x, W^T) + b)
    (gluon/nn/basic_layers.py:167). The matmul maps straight onto the MXU."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super(Dense, self).__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten,
                               name="fwd")
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({layout}, {act})"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        act=self.act if self.act else "linear",
                        layout="{0} -> {1}".format(
                            shape[1] if shape[1] else None, shape[0]))


class Dropout(HybridBlock):
    """Dropout regularization (gluon/nn/basic_layers.py:237). Uses the
    counter-based threefry RNG — inside a CachedOp trace the key is a real
    computation input, so compiled dropout stays fresh per step."""

    def __init__(self, rate, axes=(), **kwargs):
        super(Dropout, self).__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd",
                             cudnn_off=False)
        return F._copy(x)

    def __repr__(self):
        s = "{name}(p = {_rate}, axes={_axes})"
        return s.format(name=self.__class__.__name__, **self.__dict__)


class BatchNorm(HybridBlock):
    """Batch normalization (gluon/nn/basic_layers.py:291)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super(BatchNorm, self).__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        if in_channels != 0:
            self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def cast(self, dtype):
        if dtype in ("float16", "bfloat16"):
            dtype = "float32"  # stats stay fp32 (reference: BN runs fp32)
        super(BatchNorm, self).cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        if F is nd and autograd.is_training() \
                and not self._kwargs["use_global_stats"]:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, name="fwd", **self._kwargs)
            with autograd.pause():
                mom = self._kwargs["momentum"]
                running_mean._data = (mom * running_mean._data +
                                      (1 - mom) * mean._data)
                running_var._data = (mom * running_var._data +
                                     (1 - mom) * var._data)
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           name="fwd", **self._kwargs)

    def __repr__(self):
        s = "{name}({content}"
        in_channels = self.gamma.shape[0]
        s += ", in_channels={0}".format(in_channels if in_channels else None)
        s += ")"
        return s.format(name=self.__class__.__name__,
                        content=", ".join(
                            "=".join([k, v.__repr__()])
                            for k, v in self._kwargs.items()))


class Embedding(HybridBlock):
    """Turns non-negative integers into dense vectors
    (gluon/nn/basic_layers.py:397). On TPU this is a one-hot matmul /
    gather chosen by XLA."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super(Embedding, self).__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        s = "{block_name}({input_dim} -> {output_dim}, {dtype})"
        return s.format(block_name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """Flattens the input to (batch, -1) (gluon/nn/basic_layers.py:459)."""

    def __init__(self, **kwargs):
        super(Flatten, self).__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    """Instance normalization (gluon/nn/basic_layers.py:480)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super(InstanceNorm, self).__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        s = "{name}({content}"
        in_channels = self.gamma.shape[0]
        s += ", in_channels={0}".format(in_channels)
        s += ")"
        return s.format(name=self.__class__.__name__,
                        content=", ".join(
                            "=".join([k, v.__repr__()])
                            for k, v in self._kwargs.items()))


class LayerNorm(HybridBlock):
    """Layer normalization over the last (or given) axis
    (gluon/nn/basic_layers.py:563)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super(LayerNorm, self).__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.LayerNorm(data, gamma=gamma, beta=beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        s = "{name}({content}"
        in_channels = self.gamma.shape[0]
        s += ", in_channels={0}".format(in_channels)
        s += ")"
        return s.format(name=self.__class__.__name__,
                        content=", ".join(
                            "=".join([k, v.__repr__()])
                            for k, v in self._kwargs.items()))


class GroupNorm(HybridBlock):
    """Group normalization (gluon/nn/basic_layers.py:657)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super(GroupNorm, self).__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "num_groups": num_groups}
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.GroupNorm(data, gamma=gamma, beta=beta,
                           num_groups=self._num_groups, eps=self._epsilon)

    def __repr__(self):
        s = "{name}({content})"
        return s.format(name=self.__class__.__name__,
                        content=", ".join(
                            "=".join([k, v.__repr__()])
                            for k, v in self._kwargs.items()))


class Lambda(Block):
    """Wraps a function as a Block (gluon/nn/basic_layers.py:727)."""

    def __init__(self, function, prefix=None):
        super(Lambda, self).__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}"
                .format(function, type(function)))

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock (gluon/nn/basic_layers.py:758)."""

    def __init__(self, function, prefix=None):
        super(HybridLambda, self).__init__(prefix=prefix)
        from ... import symbol as sym
        if isinstance(function, str):
            assert hasattr(nd, function) or hasattr(sym, function), \
                "Function name %s is not found in symbol/ndarray." % function
            func_dict = {sym: getattr(sym, function, None),
                         nd: getattr(nd, function, None)}
            self._func = func_dict
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}"
                .format(function, type(function)))

    def hybrid_forward(self, F, x, *args):
        if isinstance(self._func, dict):
            return self._func[F](x, *args)
        return self._func(F, x, *args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)


from .activations import Activation  # noqa: E402  (Dense uses it)


class Concurrent(Sequential):
    """Runs children on the same input and concatenates their outputs
    along `axis` (reference gluon/contrib/nn/basic_layers.py Concurrent;
    promoted into gluon.nn as in later MXNet)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(Concurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Concurrent layer '%s' are HybridBlocks. "
                "Consider using HybridConcurrent for the best performance."
                % self.prefix, stacklevel=2)
        Block.hybridize(self, active, **kwargs)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (one XLA fusion per parallel branch set)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(HybridConcurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping — useful as a no-op branch in Concurrent."""

    def hybrid_forward(self, F, x):
        return x
