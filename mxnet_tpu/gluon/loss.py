"""Gluon losses.

API parity target: python/mxnet/gluon/loss.py (the 14 loss HybridBlocks).
Structure is not the reference's: the per-class weighting + batch-mean
boilerplate lives once in `_ElementwiseLoss`, concrete losses only state
their pointwise residual, and the binary-cross-entropy family uses the
softplus identities  softplus(x) = relu(x) + softplus(-|x|)  and
softplus(-x) = softplus(-|x|) + relu(-x)  to collapse the reference's
three-term stable forms into single softrelu calls (XLA fuses either way;
the short form is the one a jnp author would write).
"""

import numpy as np

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss"]

from .block import HybridBlock


class Loss(HybridBlock):
    """Base loss: holds the global weight and the batch axis."""

    def __init__(self, weight, batch_axis, **kwargs):
        super(Loss, self).__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{}(batch_axis={}, w={})".format(
            self.__class__.__name__, self._batch_axis, self._weight)

    def _scale(self, F, loss, sample_weight):
        """Per-sample weighting then the constant loss weight."""
        if sample_weight is not None:
            loss = F.broadcast_mul(loss, sample_weight)
        if self._weight is not None:
            assert isinstance(self._weight, (int, float)), \
                "weight must be a number"
            loss = loss * self._weight
        return loss

    def _finish(self, F, loss, sample_weight):
        """Shared tail of every loss: weighting, then the mean over all
        non-batch axes."""
        weighted = self._scale(F, loss, sample_weight)
        return F.mean(weighted, axis=self._batch_axis, exclude=True)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _ElementwiseLoss(Loss):
    """Losses of the shape mean_over_non_batch(scale * residual(...)).

    Subclasses implement `residual(F, pred, label)`; everything else —
    label reshape, sample weighting, the non-batch mean — is shared here
    instead of repeated per class.
    """

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape_like(label, pred)
        return self._finish(F, self.residual(F, pred, label),
                            sample_weight)

    def residual(self, F, pred, label):
        raise NotImplementedError


class L2Loss(_ElementwiseLoss):
    """0.5 * w * (pred - label)^2."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super(L2Loss, self).__init__(weight, batch_axis, **kwargs)

    def residual(self, F, pred, label):
        return 0.5 * F.square(label - pred)


class L1Loss(_ElementwiseLoss):
    """w * |pred - label|."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super(L1Loss, self).__init__(weight, batch_axis, **kwargs)

    def residual(self, F, pred, label):
        return F.abs(label - pred)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE over logits (default) or probabilities (from_sigmoid=True)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super(SigmoidBinaryCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = F.reshape_like(label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                # -z*log σ(x) - (1-z)*log σ(-x)  ==  softplus(x) - x*z
                loss = F.softrelu(pred) - pred * label
            else:
                # positive term reweighted: x - x*z + (1+(pw-1)z)*softplus(-x)
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * F.softrelu(-pred)
        else:
            eps = 1e-12
            pos_term = F.log(pred + eps) * label
            if pos_weight is not None:
                pos_term = F.broadcast_mul(pos_term, pos_weight)
            loss = -(pos_term + F.log(1.0 - pred + eps) * (1.0 - label))
        return self._finish(F, loss, sample_weight)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """log-softmax + NLL; labels sparse class ids or dense distributions."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super(SoftmaxCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits else \
            F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            label = F.reshape_like(label, logp)
            loss = -F.sum(logp * label, axis=self._axis, keepdims=True)
        return self._finish(F, loss, sample_weight)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(_ElementwiseLoss):
    """label * (log label - log pred); pred already log-prob by default."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super(KLDivLoss, self).__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def residual(self, F, pred, label):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        return label * (F.log(label + 1e-12) - pred)


class CTCLoss(Loss):
    """Connectionist temporal classification over the framework CTC op."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ("NTC", "TNC"), \
            "pred layout must be 'NTC' or 'TNC', got: %s" % layout
        assert label_layout in ("NT", "TN"), \
            "label layout must be 'NT' or 'TN', got: %s" % label_layout
        self._layout = layout
        self._label_layout = label_layout
        super(CTCLoss, self).__init__(weight, label_layout.find("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last")
        return self._scale(F, loss, sample_weight)


class HuberLoss(_ElementwiseLoss):
    """Quadratic inside |err| <= rho, linear outside."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super(HuberLoss, self).__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def residual(self, F, pred, label):
        err = F.abs(label - pred)
        return F.where(err > self._rho,
                       err - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(err))


class HingeLoss(_ElementwiseLoss):
    """max(0, margin - pred*label) for signed labels."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(HingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def residual(self, F, pred, label):
        return F.relu(self._margin - pred * label)


class SquaredHingeLoss(_ElementwiseLoss):
    """max(0, margin - pred*label)^2 for signed labels."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(SquaredHingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def residual(self, F, pred, label):
        return F.square(F.relu(self._margin - pred * label))


class LogisticLoss(_ElementwiseLoss):
    """BCE over logits with 'signed' (±1) or 'binary' (0/1) labels."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super(LogisticLoss, self).__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError(
                "label_format can only be signed or binary, recieved %s."
                % label_format)
        self._label_format = label_format

    def residual(self, F, pred, label):
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0       # map {-1,1} -> {0,1}
        return F.softrelu(pred) - pred * label


class TripletLoss(Loss):
    """max(0, ||a-p||^2 - ||a-n||^2 + margin) per anchor."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(TripletLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = F.reshape_like(positive, pred)
        negative = F.reshape_like(negative, pred)
        gap = F.sum(F.square(positive - pred) - F.square(negative - pred),
                    axis=self._batch_axis, exclude=True)
        return self._scale(F, F.relu(gap + self._margin), sample_weight)


class PoissonNLLLoss(Loss):
    """NLL under Poisson; optional Stirling correction for large targets."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super(PoissonNLLLoss, self).__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = F.reshape_like(target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            # log(k!) ~ k log k - k + 0.5 log(2 pi k), applied where k > 1
            stirling = target * F.log(target) - target + \
                0.5 * F.log(2 * np.pi * target)
            loss = loss + F.where(target > 1, stirling,
                                  F.zeros_like(stirling))
        loss = self._scale(F, loss, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    """1 - cos(a, b) for label 1, max(0, cos(a, b) - margin) for label -1."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super(CosineEmbeddingLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = F.reshape_like(input1, input2)
        dot = F.sum(input1 * input2, axis=-1).reshape((-1, 1))
        norms = F.norm(input1, axis=-1).reshape((-1, 1)) * \
            F.norm(input2, axis=-1).reshape((-1, 1))
        cos_sim = dot / F.broadcast_maximum(
            norms, F.ones_like(norms) * 1e-12)
        label = label.reshape((-1, 1)) if hasattr(label, "reshape") else label
        loss = F.where(label == 1, 1.0 - cos_sim,
                       F.relu(cos_sim - self._margin))
        return self._scale(F, loss, sample_weight)
