"""gluon.contrib — estimator fit loop, contrib layers, conv RNN cells,
samplers (reference: python/mxnet/gluon/contrib/)."""

from . import estimator
from . import nn
from . import rnn
from . import data
from . import cnn
