"""placeholder — populated in this round."""
