"""Gluon Estimator — a batteries-included fit loop over Trainer.

Reference: python/mxnet/gluon/contrib/estimator/estimator.py:40. The
event-handler contract (train/epoch/batch begin/end hooks, handler
priority ordering, default Stopping/Metric/Logging handlers) matches the
reference; the loop body is the TPU-native train step: one hybridized
forward + loss + backward per batch, Trainer.step, device-side metric
updates."""

import numpy as np

from .... import metric as metric_mod
from ....context import current_context
from ... import loss as gloss
from ...trainer import Trainer
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler,
                            LoggingHandler)

__all__ = ["Estimator"]


class _LossMetric(metric_mod.EvalMetric):
    """Running mean of the loss values (reference uses metric.Loss)."""

    _is_loss_metric = True

    def __init__(self, name="loss"):
        super(_LossMetric, self).__init__(name)

    def update(self, _labels, losses):
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        for l in losses:
            arr = l.asnumpy() if hasattr(l, "asnumpy") else np.asarray(l)
            self.sum_metric += float(arr.sum())
            self.num_inst += arr.size


class Estimator(object):
    """Train/evaluate a Gluon net with event handlers."""

    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        if not isinstance(loss, gloss.Loss):
            raise ValueError("loss must be a gluon.loss.Loss instance")
        self.loss = loss
        metrics = metrics or []
        self.train_metrics = metrics if isinstance(metrics, list) \
            else [metrics]
        for m in self.train_metrics:
            if not isinstance(m, metric_mod.EvalMetric):
                raise ValueError(
                    "metrics must be EvalMetric instances, got %r" % (m,))
        self.train_metrics.append(_LossMetric("train_" +
                                              type(loss).__name__.lower()))
        self.context = context or current_context()
        params = self.net.collect_params()
        if initializer is not None:
            self.net.initialize(initializer, force_reinit=True)
        elif any(p._data is None and not p._deferred_init
                 for p in params.values()):
            self.net.initialize()
        if trainer is None:
            trainer = Trainer(params, "adam",
                              {"learning_rate": 1e-3})
        if not isinstance(trainer, Trainer):
            raise ValueError("trainer must be a gluon.Trainer")
        self.trainer = trainer
        self.val_metrics = [_LossMetric("validation_" +
                                        type(loss).__name__.lower())]

    # ------------------------------------------------------------ eval --
    def evaluate_batch(self, batch, val_metrics, batch_axis=0):
        data, label = batch[0], batch[1]
        pred = self.net(data)
        loss = self.loss(pred, label)
        for m in val_metrics:
            if getattr(m, "_is_loss_metric", False):
                m.update(0, loss)
            else:
                m.update(label, pred)

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        val_metrics = val_metrics or self.val_metrics
        for m in val_metrics:
            m.reset()
        for batch in val_data:
            self.evaluate_batch(_as_pair(batch), val_metrics, batch_axis)
        return val_metrics

    # ------------------------------------------------------------- fit --
    def fit_batch(self, batch, batch_axis=0):
        from .... import autograd
        data, label = batch[0], batch[1]
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        batch_size = data.shape[batch_axis]
        self.trainer.step(batch_size)
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_axis=0):
        if not epochs and not batches:
            epochs = 1
        event_handlers = self._prepare_handlers(event_handlers, val_data,
                                                epochs, batches)
        groups = _dispatch_groups(event_handlers)
        stop = False
        for h in groups["train_begin"]:
            h.train_begin(self)
        while not stop:
            for h in groups["epoch_begin"]:
                h.epoch_begin(self)
            for batch in train_data:
                batch = _as_pair(batch)
                for h in groups["batch_begin"]:
                    h.batch_begin(self, batch=batch)
                data, label, pred, loss = self.fit_batch(batch,
                                                         batch_axis)
                for h in groups["batch_end"]:
                    if h.batch_end(self, batch=batch, pred=pred,
                                   label=label, loss=loss):
                        stop = True
                if stop:
                    break
            if stop:
                break
            for h in groups["epoch_end"]:
                if h.epoch_end(self):
                    stop = True
        for h in groups["train_end"]:
            h.train_end(self)

    def _prepare_handlers(self, event_handlers, val_data, epochs,
                          batches):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler)
                        for h in handlers):
            handlers.append(ValidationHandler(
                val_data, eval_fn=lambda val_data:
                self.evaluate(val_data)))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))
        return handlers


def _as_pair(batch):
    if isinstance(batch, (list, tuple)):
        return batch
    # mx.io DataBatch
    return (batch.data[0], batch.label[0])


def _dispatch_groups(handlers):
    """Sort handlers into per-event lists ordered by priority (lower
    runs first; handlers without priority run in registration order)."""
    events = {"train_begin": TrainBegin, "epoch_begin": EpochBegin,
              "batch_begin": BatchBegin, "batch_end": BatchEnd,
              "epoch_end": EpochEnd, "train_end": TrainEnd}
    groups = {}
    for key, base in events.items():
        group = [h for h in handlers if isinstance(h, base)]
        group.sort(key=lambda h: getattr(h, "priority", 0))
        groups[key] = group
    return groups
