"""Estimator event handlers.

Reference: python/mxnet/gluon/contrib/estimator/event_handler.py — the
mixin classes (TrainBegin..BatchEnd) and the stock handlers. Bodies are
original; the hook-method contract matches the reference so user
handlers port over unchanged."""

import logging
import os
import time

import numpy as np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin(object):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(object):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(object):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(object):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(object):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(object):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop training at a max epoch or batch count."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Resets train metrics each epoch and updates them per batch."""

    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []
        # run before other handlers that read metric values
        self.priority = -np.inf

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        for metric in self.train_metrics:
            if getattr(metric, "_is_loss_metric", False):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs validation every `epoch_period` epochs (or `batch_period`
    batches)."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 batch_period=None, priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                     BatchBegin, BatchEnd):
    """Logs training progress at epoch (default) or batch granularity."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval="epoch", metrics=None):
        if log_interval != "epoch" and not isinstance(log_interval, int):
            raise ValueError(
                "log_interval must be 'epoch' or an integer batch count")
        self.metrics = metrics or []
        self.log_interval = log_interval
        self.logger = logging.getLogger(__name__)
        self.priority = np.inf  # run last, after metrics updated
        self._train_start = None
        self._batch_count = 0
        self._epoch_start = None
        self.current_epoch = 0

    def _fmt_metrics(self):
        return ", ".join("%s: %.4f" % (m.get()[0], _scalar(m.get()[1]))
                         for m in self.metrics)

    def train_begin(self, estimator, *args, **kwargs):
        self._train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training done in %.3fs; %s",
                         time.time() - self._train_start,
                         self._fmt_metrics())

    def epoch_begin(self, estimator, *args, **kwargs):
        self._epoch_start = time.time()
        self._batch_count = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.logger.info("[Epoch %d] finished in %.3fs: %s",
                         self.current_epoch,
                         time.time() - self._epoch_start,
                         self._fmt_metrics())
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        self._batch_count += 1
        if isinstance(self.log_interval, int) and \
                self._batch_count % self.log_interval == 0:
            self.logger.info("[Epoch %d][Batch %d] %s",
                             self.current_epoch, self._batch_count,
                             self._fmt_metrics())


def _scalar(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Saves model parameters (and trainer states) every epoch_period
    epochs; optionally keeps the best checkpoint by a monitored metric."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.verbose = verbose
        self.current_epoch = 0
        self.current_batch = 0
        self.saved_checkpoints = []
        if save_best and monitor is None:
            raise ValueError(
                "save_best requires a monitor metric")
        if mode == "min" or (mode == "auto" and monitor is not None and
                             "acc" not in monitor.get()[0].lower()):
            self._better = lambda new, best: new < best
            self.best = np.inf
        else:
            self._better = lambda new, best: new > best
            self.best = -np.inf

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.current_batch = 0

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir,
                            "%s-%s.params" % (self.model_prefix, tag))
        estimator.net.save_parameters(path)
        if estimator.trainer is not None:
            try:
                estimator.trainer.save_states(path + ".states")
            except Exception:
                pass
        self.saved_checkpoints.append(path)
        while len(self.saved_checkpoints) > self.max_checkpoints:
            old = self.saved_checkpoints.pop(0)
            for f in (old, old + ".states"):
                if os.path.exists(f):
                    os.remove(f)
        return path

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self._save(estimator, "batch%d" % self.current_batch)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self._save(estimator, "epoch%d" % self.current_epoch)
        if self.save_best:
            val = _scalar(self.monitor.get()[1])
            if self._better(val, self.best):
                self.best = val
                path = os.path.join(
                    self.model_dir, "%s-best.params" % self.model_prefix)
                estimator.net.save_parameters(path)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stops training when the monitored metric stops improving."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        name = monitor.get()[0].lower()
        if mode == "min" or (mode == "auto" and "acc" not in name):
            self._better = lambda new, best: new < best - self.min_delta
            self._best_init = np.inf
        else:
            self._better = lambda new, best: new > best + self.min_delta
            self._best_init = -np.inf
        self.best = self._best_init

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        self.best = self.baseline if self.baseline is not None \
            else self._best_init

    def epoch_end(self, estimator, *args, **kwargs):
        val = _scalar(self.monitor.get()[1])
        if self._better(val, self.best):
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch:
            logging.getLogger(__name__).info(
                "Early stopping at epoch %d (best %s: %.4f)",
                self.stopped_epoch, self.monitor.get()[0], self.best)
