"""Deformable convolution block (reference:
gluon/contrib/cnn/conv_layers.py DeformableConvolution). An internal
ordinary convolution predicts per-tap sampling offsets; the deformable
op (ops/vision_ops.py `_contrib_DeformableConvolution`) bilinearly
samples at those offsets and contracts on the MXU."""

from ...block import HybridBlock


class DeformableConvolution(HybridBlock):
    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None,
                 weight_initializer=None, bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 prefix=None, params=None):
        super(DeformableConvolution, self).__init__(prefix=prefix,
                                                    params=params)
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        if isinstance(strides, int):
            strides = (strides, strides)
        if isinstance(padding, int):
            padding = (padding, padding)
        if isinstance(dilation, int):
            dilation = (dilation, dilation)
        assert layout == "NCHW", "deformable conv supports NCHW"
        self._channels = channels
        self._kernel = tuple(kernel_size)
        self._strides = tuple(strides)
        self._padding = tuple(padding)
        self._dilation = tuple(dilation)
        self._groups = groups
        self._ndg = num_deformable_group
        self._use_bias = use_bias
        self._act = activation

        offset_channels = 2 * self._kernel[0] * self._kernel[1] * \
            num_deformable_group
        self.offset_weight = self.params.get(
            "offset_weight",
            shape=(offset_channels, in_channels) + self._kernel,
            init=offset_weight_initializer, allow_deferred_init=True)
        self.offset_bias = self.params.get(
            "offset_bias", shape=(offset_channels,),
            init=offset_bias_initializer,
            allow_deferred_init=True) if offset_use_bias else None
        self.weight = self.params.get(
            "weight", shape=(channels, in_channels) + self._kernel,
            init=weight_initializer, allow_deferred_init=True)
        self.bias = self.params.get(
            "bias", shape=(channels,), init=bias_initializer,
            allow_deferred_init=True) if use_bias else None

    def hybrid_forward(self, F, x, offset_weight, weight, bias=None,
                       offset_bias=None):
        offset = F.Convolution(
            x, offset_weight, offset_bias,
            kernel=self._kernel, stride=self._strides, pad=self._padding,
            dilate=self._dilation,
            num_filter=2 * self._kernel[0] * self._kernel[1] * self._ndg,
            no_bias=offset_bias is None)
        out = F._contrib_DeformableConvolution(
            x, offset, weight, bias, kernel=self._kernel,
            stride=self._strides, pad=self._padding, dilate=self._dilation,
            num_filter=self._channels, num_group=self._groups,
            num_deformable_group=self._ndg, no_bias=bias is None)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out
