"""Contrib neural-network layers.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py (Concurrent,
HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle1D/2D/3D). Implementations are original; SyncBatchNorm is
TPU-native — see its docstring."""

from ... import nn
from ...block import Block, HybridBlock
from ...nn import BatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity",
           "SparseEmbedding", "SyncBatchNorm", "PixelShuffle1D",
           "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(nn.Sequential):
    """Feeds the input to every child and concatenates their outputs
    along `axis`."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(Concurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        return nd.concat(*[child(x) for child in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(HybridConcurrent, self).__init__(prefix=prefix,
                                               params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[child(x) for child in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Returns its input — the skip-connection placeholder for
    Concurrent blocks."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with sparse_grad semantics. TPU-native note: XLA has no
    sparse memory ops, so the gradient is a dense scatter-add (SURVEY §7
    hard part (a)); the class exists for API parity and behaves exactly
    like Embedding(sparse_grad=True) in the reference's forward."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super(SparseEmbedding, self).__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray as nd
        return nd.Embedding(x, self.weight.data(), **self._kwargs)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim}, " \
            "{dtype})".format(**self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm.

    Reference: src/operator/contrib/sync_batch_norm.cc — a key-slot
    barrier that all-reduces mean/var across GPUs through the engine.
    TPU-native: under GSPMD the batch axis is a *global* array dimension
    sharded over 'dp', so the plain BatchNorm reduction already spans
    every device — XLA inserts the psum over dp automatically. This
    subclass therefore only keeps the reference's signature
    (num_devices is accepted and unused) and documents the semantics:
    statistics are exact global-batch statistics, which is what the
    reference op approximates with its engine barrier."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super(SyncBatchNorm, self).__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class _PixelShuffle(HybridBlock):
    ndim = None

    def __init__(self, factor):
        super(_PixelShuffle, self).__init__()
        if isinstance(factor, int):
            factor = (factor,) * self.ndim
        self._factors = tuple(int(f) for f in factor)
        assert len(self._factors) == self.ndim


class PixelShuffle1D(_PixelShuffle):
    """[N, C*f, W] -> [N, C, W*f] sub-pixel upsampling (Shi et al. 2016).
    Pure reshape/transpose — free under XLA. Uses MXNet reshape codes
    (0 copy, -1 infer, -3 merge, -4 split) so it stays hybridizable."""

    ndim = 1

    def hybrid_forward(self, F, x):
        f, = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f, 0))       # N, C, f, W
        x = F.transpose(x, axes=(0, 1, 3, 2))            # N, C, W, f
        return F.reshape(x, shape=(0, 0, -3))            # N, C, W*f


class PixelShuffle2D(_PixelShuffle):
    """[N, C*fh*fw, H, W] -> [N, C, H*fh, W*fw]."""

    ndim = 2

    def hybrid_forward(self, F, x):
        fh, fw = self._factors
        x = F.reshape(x, shape=(0, -4, -1, fh * fw, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, fh, fw, 0, 0))  # N,C,fh,fw,H,W
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))       # N,C,H,fh,W,fw
        return F.reshape(x, shape=(0, 0, -3, -3))


class PixelShuffle3D(_PixelShuffle):
    """[N, C*fd*fh*fw, D, H, W] -> [N, C, D*fd, H*fh, W*fw]."""

    ndim = 3

    def hybrid_forward(self, F, x):
        fd, fh, fw = self._factors
        x = F.reshape(x, shape=(0, -4, -1, fd * fh * fw, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, fd, fh * fw, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, fh, fw, 0, 0, 0))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(0, 0, -3, -3, -3))
