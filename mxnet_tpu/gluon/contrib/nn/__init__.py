"""Contrib layers (reference: gluon/contrib/nn/)."""
from .basic_layers import *  # noqa: F401,F403
from .basic_layers import __all__  # noqa: F401
