"""Contrib data utilities (reference: gluon/contrib/data/)."""
from .sampler import IntervalSampler

__all__ = ["IntervalSampler"]
