"""Contrib data utilities (reference: gluon/contrib/data/)."""
from .sampler import IntervalSampler
from . import text
from .text import WikiText2, WikiText103

__all__ = ["IntervalSampler", "WikiText2", "WikiText103"]
