"""Language-modeling text datasets (reference:
gluon/contrib/data/text.py WikiText2/WikiText103).

This environment has no egress, so the archive download step is
replaced by reading pre-placed token files from `root` (the same
`wiki.{train,valid,test}.tokens` layout the reference unpacks). A clear
error names the missing file instead of attempting a fetch.
"""

import io
import os

import numpy as np

from .... import ndarray as nd


def _data_dir():
    return os.environ.get("MXNET_HOME", os.path.join(
        os.path.expanduser("~"), ".mxnet"))


class _WikiText(object):
    SEGMENT_FILES = {"train": "wiki.train.tokens",
                     "validation": "wiki.valid.tokens",
                     "test": "wiki.test.tokens"}

    def __init__(self, root, segment, vocab, seq_len):
        if segment not in self.SEGMENT_FILES:
            raise ValueError("segment must be one of %s"
                             % sorted(self.SEGMENT_FILES))
        path = os.path.join(os.path.expanduser(root),
                            self.SEGMENT_FILES[segment])
        if not os.path.exists(path):
            raise IOError(
                "%s not found. This build cannot download datasets "
                "(no network egress); place the extracted WikiText "
                "token files under %r first." % (path, root))
        with io.open(path, encoding="utf-8") as f:
            tokens = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                tokens.extend(line.split() + ["<eos>"])
        if vocab is None:
            # always include <unk> so this vocab can code other segments
            # (reference maps out-of-vocabulary tokens to <unk>, never
            # drops them — dropping would shift the token stream and the
            # data/label alignment)
            uniq = sorted(set(tokens) | {"<unk>"})
            vocab = {w: i for i, w in enumerate(uniq)}
        self.vocabulary = vocab
        unk = vocab.get("<unk>")
        if unk is None and any(w not in vocab for w in tokens):
            raise ValueError(
                "the supplied vocabulary has out-of-vocabulary tokens in "
                "segment %r but no '<unk>' entry to map them to" % segment)
        coded = np.asarray([vocab.get(w, unk) for w in tokens],
                           dtype=np.float32)
        n = (len(coded) - 1) // seq_len
        data = coded[:n * seq_len].reshape(n, seq_len)
        label = coded[1:n * seq_len + 1].reshape(n, seq_len)
        self._samples = [nd.array(d) for d in data]
        self._labels = [nd.array(l) for l in label]

    def __getitem__(self, idx):
        return self._samples[idx], self._labels[idx]

    def __len__(self):
        return len(self._samples)


class WikiText2(_WikiText):
    """WikiText-2 word-level LM dataset (local token files)."""

    def __init__(self, root=None, segment="train", vocab=None, seq_len=35):
        root = root or os.path.join(_data_dir(), "datasets", "wikitext-2")
        super(WikiText2, self).__init__(root, segment, vocab, seq_len)


class WikiText103(_WikiText):
    """WikiText-103 word-level LM dataset (local token files)."""

    def __init__(self, root=None, segment="train", vocab=None, seq_len=35):
        root = root or os.path.join(_data_dir(), "datasets",
                                    "wikitext-103")
        super(WikiText103, self).__init__(root, segment, vocab, seq_len)
