"""Contrib samplers (reference:
python/mxnet/gluon/contrib/data/sampler.py)."""

from ...data import sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(sampler.Sampler):
    """Samples [0, s, 2s, ...], then [1, s+1, 2s+1, ...], etc. —
    interval-strided coverage of [0, length)."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise ValueError(
                "interval %d must not exceed length %d"
                % (interval, length))
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for start in range(self._interval if self._rollover else 1):
            for i in range(start, self._length, self._interval):
                yield i

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
