"""Contrib recurrent cells (reference: gluon/contrib/rnn/)."""
from .conv_rnn_cell import *  # noqa: F401,F403
from .conv_rnn_cell import __all__  # noqa: F401
