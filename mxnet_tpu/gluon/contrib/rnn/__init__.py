"""Contrib recurrent cells (reference: gluon/contrib/rnn/)."""
from .conv_rnn_cell import *  # noqa: F401,F403
from .rnn_cell import LSTMPCell, VariationalDropoutCell  # noqa: F401
from . import conv_rnn_cell, rnn_cell

__all__ = list(conv_rnn_cell.__all__) + list(rnn_cell.__all__)
