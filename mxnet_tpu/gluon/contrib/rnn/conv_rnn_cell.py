"""Convolutional recurrent cells (ConvRNN / ConvLSTM / ConvGRU, 1D-3D).

Reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py (Shi et al.
2015, "Convolutional LSTM Network"). The gate pre-activations are
convolutions over spatial feature maps instead of dense products; state
shape equals the hidden feature map. Gate order matches the dense cells
(LSTM: i, f, g, o; GRU: r, z, n) so fused-op parity tests carry over.

TPU note: the gate convs are stacked into one Convolution per
input/state (num_filter = gates*hidden) — one big MXU-friendly conv
instead of `gates` small ones."""

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvRNNCellBase(HybridRecurrentCell):
    """Shared machinery: conv weights for input->hidden and
    hidden->hidden gate stacks, spatial state info."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, dims, conv_layout, activation,
                 prefix=None, params=None):
        super(_ConvRNNCellBase, self).__init__(prefix=prefix,
                                               params=params)
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._dims = dims
        self._activation = activation
        self._conv_layout = conv_layout
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    "h2h_kernel dims must be odd to preserve the state's "
                    "spatial shape, got %s" % (self._h2h_kernel,))
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._h2h_pad = tuple((k - 1) // 2 for k in self._h2h_kernel)
        in_ch = self._input_shape[0]
        ngates = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(ngates * hidden_channels, in_ch) + self._i2h_kernel,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(ngates * hidden_channels, hidden_channels) +
            self._h2h_kernel,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ngates * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ngates * hidden_channels,), init="zeros",
            allow_deferred_init=True)

    def _spatial_out(self):
        # i2h conv output spatial dims (stride 1): s + 2p - k + 1
        return tuple(s + 2 * p - k + 1 for s, p, k in
                     zip(self._input_shape[1:], self._i2h_pad,
                         self._i2h_kernel))

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._spatial_out()
        return [{"shape": shape, "__layout__": self._conv_layout}] * \
            self._num_states

    def _conv_gates(self, F, inputs, state, i2h_weight, h2h_weight,
                    i2h_bias, h2h_bias, prefix):
        n_out = self._num_gates * self._hidden_channels
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=n_out, name=prefix + "i2h")
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=n_out, name=prefix + "h2h")
        return i2h, h2h


class _ConvRNNCell(_ConvRNNCellBase):
    _num_gates = 1
    _num_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h, h2h = self._conv_gates(F, inputs, states[0], i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias,
                                    prefix)
        out = F.Activation(i2h + h2h, act_type=self._activation,
                           name=prefix + "out")
        return out, [out]


class _ConvLSTMCell(_ConvRNNCellBase):
    _num_gates = 4
    _num_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h, h2h = self._conv_gates(F, inputs, states[0], i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias,
                                    prefix)
        gates = F.SliceChannel(i2h + h2h, num_outputs=4, axis=1,
                               name=prefix + "slice")
        i = F.sigmoid(gates[0])
        f = F.sigmoid(gates[1])
        g = F.Activation(gates[2], act_type=self._activation)
        o = F.sigmoid(gates[3])
        next_c = f * states[1] + i * g
        next_h = o * F.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_ConvRNNCellBase):
    _num_gates = 3
    _num_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h, h2h = self._conv_gates(F, inputs, states[0], i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias,
                                    prefix)
        i2h_g = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_g = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(i2h_g[0] + h2h_g[0])
        z = F.sigmoid(i2h_g[1] + h2h_g[1])
        n = F.Activation(i2h_g[2] + r * h2h_g[2],
                         act_type=self._activation)
        next_h = (1.0 - z) * n + z * states[0]
        return next_h, [next_h]


def _make_cell(base, dims, layout, alias):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, conv_layout=layout,
                     activation="tanh", prefix=None, params=None):
            super(Cell, self).__init__(
                input_shape=input_shape,
                hidden_channels=hidden_channels, i2h_kernel=i2h_kernel,
                h2h_kernel=h2h_kernel, i2h_pad=i2h_pad, dims=dims,
                conv_layout=conv_layout, activation=activation,
                prefix=prefix, params=params)
    Cell.__name__ = alias
    Cell.__qualname__ = alias
    return Cell


Conv1DRNNCell = _make_cell(_ConvRNNCell, 1, "NCW", "Conv1DRNNCell")
Conv2DRNNCell = _make_cell(_ConvRNNCell, 2, "NCHW", "Conv2DRNNCell")
Conv3DRNNCell = _make_cell(_ConvRNNCell, 3, "NCDHW", "Conv3DRNNCell")
Conv1DLSTMCell = _make_cell(_ConvLSTMCell, 1, "NCW", "Conv1DLSTMCell")
Conv2DLSTMCell = _make_cell(_ConvLSTMCell, 2, "NCHW", "Conv2DLSTMCell")
Conv3DLSTMCell = _make_cell(_ConvLSTMCell, 3, "NCDHW", "Conv3DLSTMCell")
Conv1DGRUCell = _make_cell(_ConvGRUCell, 1, "NCW", "Conv1DGRUCell")
Conv2DGRUCell = _make_cell(_ConvGRUCell, 2, "NCHW", "Conv2DGRUCell")
Conv3DGRUCell = _make_cell(_ConvGRUCell, 3, "NCDHW", "Conv3DGRUCell")
