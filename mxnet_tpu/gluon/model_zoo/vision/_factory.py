"""Shared builder-factory for the zoo's named entry points.

Every family exposes a flat set of `name -> fixed-config` constructors
(resnet50_v1, vgg16_bn, mobilenet0_25, ...); each is the family getter
with some arguments pinned. One helper stamps them all so identity
metadata (__name__/__qualname__/__doc__) is handled in one place.
"""


def entry_point(name, doc, getter, *pinned, **fixed_kwargs):
    """A public constructor `name` that calls ``getter(*pinned,
    **fixed_kwargs, **caller_kwargs)``."""
    def build(**kwargs):
        merged = dict(fixed_kwargs)
        merged.update(kwargs)
        return getter(*pinned, **merged)
    build.__name__ = build.__qualname__ = name
    build.__doc__ = doc
    return build
