"""AlexNet (Krizhevsky et al. 2012) for the Gluon model zoo.

Reference API: python/mxnet/gluon/model_zoo/vision/alexnet.py.
"""

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super(AlexNet, self).__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                # architecture spec as data: (channels, kernel, stride,
                # pad, pool-after?) per conv stage
                for ch, k, s, pad, pool in ((64, 11, 4, 2, True),
                                            (192, 5, 1, 2, True),
                                            (384, 3, 1, 1, False),
                                            (256, 3, 1, 1, False),
                                            (256, 3, 1, 1, True)):
                    self.features.add(nn.Conv2D(
                        ch, kernel_size=k, strides=s, padding=pad,
                        activation="relu"))
                    if pool:
                        self.features.add(
                            nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Flatten())
                for _ in range(2):
                    self.features.add(nn.Dense(4096, activation="relu"),
                                      nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def alexnet(pretrained=False, ctx=cpu(), **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        raise ValueError("pretrained weights are not available in mxnet_tpu")
    return net
