"""VGG (Simonyan & Zisserman 2014) for the Gluon model zoo.

Reference API: python/mxnet/gluon/model_zoo/vision/vgg.py — vgg11/13/16/19
plus the batch-norm variants.
"""

from ....context import cpu
from ....initializer import Xavier
from ...block import HybridBlock
from ._factory import entry_point
from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg"]


class VGG(HybridBlock):
    """`layers` and `filters` give the per-stage conv counts and widths."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super(VGG, self).__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal",
                                       bias_initializer="zeros"))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal",
                                       bias_initializer="zeros"))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer="normal",
                                   bias_initializer="zeros")

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(
                    filters[i], kernel_size=3, padding=1,
                    weight_initializer=Xavier(rnd_type="gaussian",
                                              factor_type="out",
                                              magnitude=2),
                    bias_initializer="zeros"))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation("relu"))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_vgg(num_layers, pretrained=False, ctx=cpu(), **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        raise ValueError("pretrained weights are not available in mxnet_tpu")
    return net


def _vgg_entry(depth, batch_norm):
    suffix = "_bn" if batch_norm else ""
    fixed = {"batch_norm": True} if batch_norm else {}
    return entry_point(
        "vgg%d%s" % (depth, suffix),
        "VGG-%d model%s." % (depth, " with batch normalization"
                             if batch_norm else ""),
        get_vgg, depth, **fixed)


vgg11 = _vgg_entry(11, False)
vgg13 = _vgg_entry(13, False)
vgg16 = _vgg_entry(16, False)
vgg19 = _vgg_entry(19, False)
vgg11_bn = _vgg_entry(11, True)
vgg13_bn = _vgg_entry(13, True)
vgg16_bn = _vgg_entry(16, True)
vgg19_bn = _vgg_entry(19, True)
