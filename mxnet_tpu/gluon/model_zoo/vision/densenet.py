"""DenseNet (Huang et al. 2016) for the Gluon model zoo.

Reference API: python/mxnet/gluon/model_zoo/vision/densenet.py —
densenet121/161/169/201.
"""

from ....context import cpu
from ...block import HybridBlock
from ._factory import entry_point
from ... import nn
from ...nn import HybridConcurrent, Identity

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "get_densenet"]


def _make_dense_block(num_layers, bn_size, growth_rate, dropout, stage_index):
    out = nn.HybridSequential(prefix="stage%d_" % stage_index)
    with out.name_scope():
        for _ in range(num_layers):
            out.add(_make_dense_layer(growth_rate, bn_size, dropout))
    return out


def _bn_relu_conv(seq, channels, kernel, padding=0):
    """The BN -> ReLU -> conv triplet every DenseNet component repeats."""
    seq.add(nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(channels, kernel_size=kernel, padding=padding,
                      use_bias=False))


def _make_dense_layer(growth_rate, bn_size, dropout):
    new_features = nn.HybridSequential(prefix="")
    _bn_relu_conv(new_features, bn_size * growth_rate, 1)
    _bn_relu_conv(new_features, growth_rate, 3, padding=1)
    if dropout:
        new_features.add(nn.Dropout(dropout))
    # dense connectivity: the layer's output rides alongside its input
    out = HybridConcurrent(axis=1, prefix="")
    out.add(Identity(), new_features)
    return out


def _make_transition(num_output_features):
    out = nn.HybridSequential(prefix="")
    _bn_relu_conv(out, num_output_features, 1)
    out.add(nn.AvgPool2D(pool_size=2, strides=2))
    return out


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super(DenseNet, self).__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(
                nn.Conv2D(num_init_features, kernel_size=7, strides=2,
                          padding=3, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.MaxPool2D(pool_size=3, strides=2, padding=1))
            width = num_init_features
            last = len(block_config) - 1
            for i, num_layers in enumerate(block_config):
                self.features.add(_make_dense_block(
                    num_layers, bn_size, growth_rate, dropout, i + 1))
                width += num_layers * growth_rate
                if i != last:
                    width //= 2
                    self.features.add(_make_transition(width))
            self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                              nn.AvgPool2D(pool_size=7), nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


# num_layers -> (num_init_features, growth_rate, per-stage layer counts)
densenet_spec = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


def get_densenet(num_layers, pretrained=False, ctx=cpu(), **kwargs):
    num_init_features, growth_rate, block_config = densenet_spec[num_layers]
    net = DenseNet(num_init_features, growth_rate, block_config, **kwargs)
    if pretrained:
        raise ValueError("pretrained weights are not available in mxnet_tpu")
    return net


def _densenet_entry(depth):
    return entry_point("densenet%d" % depth,
                       "DenseNet-%d model." % depth,
                       get_densenet, depth)


densenet121 = _densenet_entry(121)
densenet161 = _densenet_entry(161)
densenet169 = _densenet_entry(169)
densenet201 = _densenet_entry(201)
