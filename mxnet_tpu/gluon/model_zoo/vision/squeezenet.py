"""SqueezeNet 1.0/1.1 (Iandola et al. 2016) for the Gluon model zoo.

Reference API: python/mxnet/gluon/model_zoo/vision/squeezenet.py.
"""

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1", "get_squeezenet"]


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential(prefix="")
    out.add(_make_fire_conv(squeeze_channels, 1))

    paths = nn.HybridConcurrent(axis=1, prefix="")
    paths.add(_make_fire_conv(expand1x1_channels, 1))
    paths.add(_make_fire_conv(expand3x3_channels, 3, 1))
    out.add(paths)
    return out


def _make_fire_conv(channels, kernel_size, padding=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel_size, padding=padding))
    out.add(nn.Activation("relu"))
    return out


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super(SqueezeNet, self).__init__(**kwargs)
        assert version in ("1.0", "1.1"), \
            "Unsupported SqueezeNet version %s: 1.0 or 1.1 expected" % version
        with self.name_scope():
            # stem conv spec + fire-module schedule ("pool" rows are
            # the 3x3/2 ceil-mode max pools) — the two versions differ
            # only in this data
            stem, schedule = {
                "1.0": ((96, 7), ["pool", (16, 64), (16, 64), (32, 128),
                                  "pool", (32, 128), (48, 192),
                                  (48, 192), (64, 256), "pool",
                                  (64, 256)]),
                "1.1": ((64, 3), ["pool", (16, 64), (16, 64), "pool",
                                  (32, 128), (32, 128), "pool",
                                  (48, 192), (48, 192), (64, 256),
                                  (64, 256)]),
            }[version]
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(stem[0], kernel_size=stem[1],
                                        strides=2),
                              nn.Activation("relu"))
            for step in schedule:
                if step == "pool":
                    self.features.add(nn.MaxPool2D(
                        pool_size=3, strides=2, ceil_mode=True))
                else:
                    squeeze, expand = step
                    self.features.add(_make_fire(squeeze, expand, expand))
            self.features.add(nn.Dropout(0.5))

            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def get_squeezenet(version, pretrained=False, ctx=cpu(), **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        raise ValueError("pretrained weights are not available in mxnet_tpu")
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet("1.1", **kwargs)
