"""Gluon Block / HybridBlock / SymbolBlock.

Reference: python/mxnet/gluon/block.py (Block:131, HybridBlock:705,
SymbolBlock:992; hybridize -> _build_cache:786 -> CachedOp:823).

TPU-native design: ``hybridize()`` traces ``hybrid_forward`` with Symbol
proxies (exactly like the reference) and wraps the traced graph in a
CachedOp whose execution is ONE jit-compiled XLA computation
(mxnet_tpu/cached_op.py) — the natural TPU realization of the reference's
static_alloc/static_shape fast path, with XLA doing memory planning and
fusion instead of MXPlanMemory/bulking.
"""

import copy
import re
import threading

from .. import autograd
from .. import name as _name
from .. import ndarray as nd
from .. import symbol as _symbol
from ..base import MXNetError
from ..cached_op import CachedOp
from ..context import current_context
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

_naming = threading.local()


def _global_counter():
    if not hasattr(_naming, "counter"):
        _naming.counter = {}
    return _naming.counter


class _BlockScope(object):
    """Name-manager scope for nested Blocks (gluon/block.py:35)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scopes = []       # stack: restore targets per entry
        self._name_managers = []    # stack: one fresh Prefix per entry

    @staticmethod
    def create(prefix, params, hint):
        """Creates prefix and params for new `Block`."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                counter = _global_counter()
                count = counter.get(hint, 0)
                counter[hint] = count + 1
                prefix = "%s%d_" % (hint, count)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params

        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scopes.append(getattr(_BlockScope._current, "value", None))
        _BlockScope._current.value = self
        # ops composed inside this scope — including explicitly-named ones
        # like the layer-internal name='fwd' — get the block prefix, so
        # node names stay unique across sibling blocks (the reference
        # enters _name.Prefix(block.prefix) the same way). A fresh Prefix
        # per entry keeps nested/concurrent entries reentrant: NameManager
        # stores its restore pointer on the instance.
        manager = _name.Prefix(self._block.prefix)
        manager.__enter__()
        self._name_managers.append(manager)
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_managers.pop().__exit__(ptype, value, trace)
        _BlockScope._current.value = self._old_scopes.pop()


def _flatten(args, fmt_name):
    """Flatten nested list/tuple structure of NDArrays/Symbols; returns
    (flat_list, format_tree) (gluon/block.py:53)."""
    if isinstance(args, (nd.NDArray, _symbol.Symbol)):
        return [args], int(0)
    if args is None:
        return [None], int(-1)
    if not isinstance(args, (list, tuple)):
        raise ValueError(
            "When hybridized, the input of HybridBlock {} must be (nested) "
            "list of Symbol or NDArray, but got {} of type {}"
            .format(fmt_name, str(args), str(type(args))))
    flat, fmts = [], []
    for i in args:
        arg, fmt = _flatten(i, fmt_name)
        flat += arg
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == -1:
            return None, args[1:]
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block(object):
    """Base class for all neural network layers and models
    (python/mxnet/gluon/block.py:131).

    Childs and Parameters set as attributes are registered automatically;
    ``collect_params()`` returns the full ParameterDict of the subtree.
    """

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(
                key=key, block=re.sub("\n", "\n  ", repr(block)))
            for key, block in self.__dict__.items()
            if isinstance(block, Block))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError(
                    "Changing attribute type for {name} from {type1} to "
                    "{type2} is not allowed.".format(
                        name=name, type1=type(existing), type2=type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed. " \
                "If you want to share parameters between blocks, please set " \
                "'params' at Block construction instead." % name
            self._reg_params[name] = value
        super(Block, self).__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    # ---------------------------------------------------------- naming --
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Returns a name space object managing a child Block and parameter
        names."""
        return self._scope

    @property
    def params(self):
        """Returns this Block's parameter dictionary (does not include its
        children's parameters)."""
        return self._params

    def collect_params(self, select=None):
        """Returns a ParameterDict containing this Block's and all of its
        children's Parameters, optionally filtered by regex ``select``."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # ---------------------------------------------------------- children --
    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def apply(self, fn):
        """Applies ``fn`` recursively to every child block as well as self."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    # -------------------------------------------------------------- io --
    def save_parameters(self, filename, deduplicate=False):
        """Saves parameters to file using structural naming
        (gluon/block.py:319)."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val._reduce() if hasattr(val, "_reduce")
                    else val.data() for key, val in params.items()}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Loads parameters from file (gluon/block.py:361). Accepts both
        structural-name files (save_parameters) and full-name files
        (collect_params().save)."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # contains full parameter names — legacy collect_params().save
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    "Parameter '%s' is missing in file '%s'" % (name, filename)
        for name in loaded:
            if name not in params:
                assert ignore_extra, \
                    "Parameter '%s' loaded from file '%s' is not present in " \
                    "this block" % (name, filename)
                continue
            params[name]._load_init(loaded[name], ctx, cast_dtype=cast_dtype,
                                    dtype_source=dtype_source)

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------- init --
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """Initializes Parameters of this Block and its children."""
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """Activates or deactivates HybridBlock children recursively."""
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    # ------------------------------------------------------------- call --
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        from ..util import is_np_array
        if is_np_array():
            # numpy-array semantics (util.set_np/use_np): emit
            # mx.np.ndarray wrappers over the same buffers
            from .. import numpy as _mxnp
            from ..ndarray import NDArray as _ND

            def _wrap(o):
                if isinstance(o, _ND) and not isinstance(o, _mxnp.ndarray):
                    return _mxnp.array(o._data)
                if isinstance(o, (list, tuple)):
                    return type(o)(_wrap(x) for x in o)
                return o
            out = _wrap(out)
        return out

    def forward(self, *args):
        """Overridden by users: imperative computation over NDArray."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a table of layer outputs and params for given inputs."""
        summary = []
        hooks = []

        def _register(block):
            def hook(blk, ins, outs):
                n_params = sum(
                    int(p.data().size) for p in blk.params.values()
                    if p._data is not None)
                first = outs[0] if isinstance(outs, (list, tuple)) else outs
                summary.append((blk.name, type(blk).__name__,
                                getattr(first, "shape", None), n_params))
            hooks.append(block.register_forward_hook(hook))

        self.apply(_register)
        try:
            self(*inputs)
            lines = ["%-30s %-20s %-20s %10s" %
                     ("Layer (name)", "Type", "Output Shape", "Params")]
            lines.append("-" * 84)
            total = 0
            for name, tname, shape, n in summary:
                total += n
                lines.append("%-30s %-20s %-20s %10d"
                             % (name, tname, str(shape), n))
            lines.append("-" * 84)
            lines.append("Total params: %d" % total)
            print("\n".join(lines))
        finally:
            def _clean(blk):
                blk._forward_hooks = [h for h in blk._forward_hooks
                                      if h not in hooks]
            self.apply(_clean)


class HybridBlock(Block):
    """A Block that supports hybridization: forwarding with NDArray or
    Symbol, and compilation of the traced graph via CachedOp
    (python/mxnet/gluon/block.py:705)."""

    def __init__(self, prefix=None, params=None):
        super(HybridBlock, self).__init__(prefix=prefix, params=params)
        self._cached_graph = ()
        self._cached_op = None
        self._cached_op_args = []
        self._active = False
        self._flags = []

    def __setattr__(self, name, value):
        super(HybridBlock, self).__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s. If you are using Sequential, please try "
                "HybridSequential instead." % (str(block), str(type(block))))
        super(HybridBlock, self).register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = list(kwargs.items())
        self._clear_cached_op()
        super(HybridBlock, self).hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super(HybridBlock, self).cast(dtype)

    def _clear_cached_op(self):
        self._cached_graph = ()
        self._cached_op = None
        self._cached_op_args = []

    # ------------------------------------------------------------ trace --
    def _get_graph(self, *args):
        if not self._cached_graph:
            flat_args, self._in_format = _flatten(args, "input")
            real = [a for a in flat_args if a is not None]
            if len(real) == 1:
                syms = [_symbol.var("data")]
            else:
                syms = [_symbol.var("data%d" % i) for i in range(len(real))]
            it = iter(syms)
            grouped = [next(it) if a is not None else None for a in flat_args]
            grouped_args, _ = _regroup(grouped, self._in_format)
            if not isinstance(grouped_args, (list, tuple)):
                grouped_args = [grouped_args]
            params = {name: p.var() for name, p in self._reg_params.items()}
            with self.name_scope():
                out = self.hybrid_forward(_symbol, *grouped_args, **params)
            flat_out, self._out_format = _flatten(out, "output")
            if len(flat_out) > 1:
                self._cached_graph = (syms, _symbol.Group(flat_out))
            else:
                self._cached_graph = (syms, flat_out[0])
        return self._cached_graph

    def infer_shape(self, *args):
        """Infers shape of Parameters from inputs."""
        self._deferred_infer_shape(*args)

    def infer_type(self, *args):
        """Infers dtype of Parameters from inputs (reference
        HybridBlock.infer_type). Parameters follow the input dtype —
        under the bf16 AMP policy a float16/bfloat16 example input casts
        the float parameters accordingly."""
        flat_args, _ = _flatten(args, "input")
        real = [a for a in flat_args if a is not None]
        if not real:
            return
        dtype = real[0].dtype
        import numpy as _np
        if _np.dtype(dtype).kind != "f":
            return
        for param in self.collect_params().values():
            if param._data is not None and \
                    _np.dtype(param.dtype).kind == "f":
                param.cast(dtype)

    def _deferred_infer_shape(self, *args):
        try:
            inputs, out = self._get_graph(*args)
            flat_args, _ = _flatten(args, "input")
            real = [a for a in flat_args if a is not None]
            kwargs = {i.name: a.shape for i, a in zip(inputs, real)}
            arg_shapes, _, aux_shapes = out.infer_shape_partial(**kwargs)
            sdict = dict(zip(out.list_arguments(), arg_shapes))
            sdict.update(zip(out.list_auxiliary_states(), aux_shapes))
            for name, param in self.collect_params().items():
                shp = sdict.get(name)
                if shp is not None:
                    param.shape = shp
        except Exception as e:
            raise ValueError(
                "Deferred initialization failed because shape cannot be "
                "inferred: %s" % e)

    # ------------------------------------------------------------ cache --
    def _build_cache(self, *args):
        inputs, out = self._get_graph(*args)
        input_names = out.list_inputs()
        params = {p.name: p for p in self.collect_params().values()}
        param_names = set(params.keys())
        expected_names = set(input_names)
        for name in expected_names:
            assert name in param_names or name in [i.name for i in inputs], \
                "Unknown input to HybridBlock: %s" % name

        data_names = {i.name: idx for idx, i in enumerate(inputs)}
        self._cached_op_args = []
        for name in input_names:
            if name in data_names:
                self._cached_op_args.append((True, data_names[name]))
            else:
                self._cached_op_args.append((False, params[name]))
        self._cached_op = CachedOp(out, self._flags)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._build_cache(*args)
        flat_args, fmt = _flatten(args, "input")
        real = [a for a in flat_args if a is not None]
        # arg structure changed since the trace (e.g. an RNN layer called
        # with and without explicit begin_state) -> retrace
        n_traced = sum(1 for is_data, _ in self._cached_op_args if is_data)
        if n_traced != len(real):
            self._clear_cached_op()
            self._build_cache(*args)
        cargs = []
        for is_data, data in self._cached_op_args:
            if is_data:
                cargs.append(real[data])
            else:
                cargs.append(data.data())
        out = self._cached_op(*cargs)
        if len(out) == 1 and self._out_format == 0:
            return out[0]
        ret, _ = _regroup(list(out), self._out_format)
        return ret

    # ---------------------------------------------------------- forward --
    def forward(self, x, *args):
        """Defines the forward computation; dispatches to
        ``hybrid_forward`` with F=ndarray or F=symbol."""
        if isinstance(x, nd.NDArray):
            if self._active:
                try:
                    return self._call_cached_op(x, *args)
                except DeferredInitializationError:
                    self._deferred_infer_shape(x, *args)
                    for p in self.collect_params().values():
                        p._finish_deferred_init()
                    return self._call_cached_op(x, *args)
            try:
                params = {name: p.data()
                          for name, p in self._reg_params.items()}
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for p in self.collect_params().values():
                    p._finish_deferred_init()
                params = {name: p.data()
                          for name, p in self._reg_params.items()}
            return self.hybrid_forward(nd, x, *args, **params)

        assert isinstance(x, _symbol.Symbol), \
            "HybridBlock requires the first argument to forward be either " \
            "Symbol or NDArray, but got %s" % type(x)
        params = {name: p.var() for name, p in self._reg_params.items()}
        with self.name_scope():
            return self.hybrid_forward(_symbol, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Overridden by users: computation over ``F`` (mx.nd or mx.sym)."""
        raise NotImplementedError

    # ------------------------------------------------------------ export --
    def export(self, path, epoch=0):
        """Exports traced symbol + params for deployment
        (gluon/block.py:907): path-symbol.json and path-NNNN.params."""
        if not self._cached_graph:
            raise RuntimeError(
                "Please first call block.hybridize() and then run forward "
                "with this block at least once before calling export.")
        sym = self._cached_graph[1]
        sym.save("%s-symbol.json" % path)

        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        arg_dict = {}
        for name, param in self.collect_params().items():
            if name in arg_names:
                arg_dict["arg:%s" % name] = param.data()
            elif name in aux_names:
                arg_dict["aux:%s" % name] = param.data()
        nd.save("%s-%04d.params" % (path, epoch), arg_dict)
        return sym


class SymbolBlock(HybridBlock):
    """Construct a Block from a Symbol (gluon/block.py:992) — the importer
    for exported models."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        sym = _symbol.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_symbol.var(i) for i in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx, cast_dtype=True,
                                      dtype_source="saved",
                                      allow_missing=False, ignore_extra=False)
        return ret

    def __init__(self, outputs, inputs, params=None):
        super(SymbolBlock, self).__init__(prefix=None, params=None)
        self._prefix = ""
        self._params = ParameterDict("", params)
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = _symbol.Group(outputs)
        if isinstance(inputs, _symbol.Symbol):
            inputs = [inputs]

        syms, self._in_format = _flatten(inputs, "input")
        out = outputs
        input_names = set(s.name for s in syms)

        for name in out.list_arguments():
            if name not in input_names:
                p = self._params.get(name, allow_deferred_init=True)
                self._reg_params[name] = p
        for name in out.list_auxiliary_states():
            if name not in input_names:
                p = self._params.get(name, grad_req="null",
                                     allow_deferred_init=True)
                self._reg_params[name] = p

        self._cached_graph = syms, out
        self._build_cache_from_graph()

    def _build_cache_from_graph(self):
        inputs, out = self._cached_graph
        input_names = out.list_inputs()
        params = {p.name: p for p in self._params.values()}
        data_names = {i.name: idx for idx, i in enumerate(inputs)}
        self._cached_op_args = []
        for name in input_names:
            if name in data_names:
                self._cached_op_args.append((True, data_names[name]))
            else:
                self._cached_op_args.append((False, params[name]))
        self._cached_op = CachedOp(out, self._flags)
        self._out_format = _flatten(
            [out] if len(out.list_outputs()) == 1 else
            [out[i] for i in range(len(out.list_outputs()))], "output")[1]
        if len(out.list_outputs()) == 1:
            self._out_format = 0

    def forward(self, x, *args):
        if isinstance(x, nd.NDArray):
            try:
                return self._call_cached_op(x, *args)
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for p in self._params.values():
                    p._finish_deferred_init()
                return self._call_cached_op(x, *args)
        assert isinstance(x, _symbol.Symbol), \
            "SymbolBlock requires Symbol or NDArray input"
        return self._cached_graph[1]

    def _call_cached_op(self, *args):
        flat_args, _ = _flatten(args, "input")
        real = [a for a in flat_args if a is not None]
        cargs = []
        for is_data, data in self._cached_op_args:
            if is_data:
                cargs.append(real[data])
            else:
                cargs.append(data.data())
        out = self._cached_op(*cargs)
        if len(out) == 1:
            return out[0]
        return list(out)

    def _clear_cached_op(self):
        tmp = getattr(self, "_cached_graph", ())
        super(SymbolBlock, self)._clear_cached_op()
        self._cached_graph = tmp

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
