"""Gluon Block / HybridBlock / SymbolBlock.

Reference: python/mxnet/gluon/block.py (Block:131, HybridBlock:705,
SymbolBlock:992; hybridize -> _build_cache:786 -> CachedOp:823).

TPU-native design: ``hybridize()`` traces ``hybrid_forward`` with Symbol
proxies (exactly like the reference) and wraps the traced graph in a
CachedOp whose execution is ONE jit-compiled XLA computation
(mxnet_tpu/cached_op.py) — the natural TPU realization of the reference's
static_alloc/static_shape fast path, with XLA doing memory planning and
fusion instead of MXPlanMemory/bulking.
"""

import contextlib
import re
import threading

from .. import autograd
from .. import name as _name
from .. import ndarray as nd
from .. import symbol as _symbol
from ..base import MXNetError
from ..observability import attribution as _obs_attr
from ..observability import core as _obs
from ..cached_op import CachedOp
from ..context import current_context
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

# per-thread nesting depth of Block.__call__ — the outermost call owns
# the step-phase "forward" telemetry span
_CALL_DEPTH = threading.local()


class _NamingState(threading.local):
    """Per-thread naming state: a stack of open ``name_scope`` frames
    plus the top-level hint counters.

    The auto-prefix CONTRACT is fixed by checkpoint parity with the
    reference (gluon/block.py _BlockScope): a block constructed with no
    explicit prefix is named ``<hint><index>_`` where the index counts
    hint uses within the enclosing scope (or within the thread, at top
    level), and children concatenate onto their parent's prefix. The
    mechanism here is this repo's own: one thread-local frame stack
    instead of a scope class threading save/restore pointers through
    static state.
    """

    def __init__(self):
        self.frames = []            # innermost-open-scope last
        self.top_counts = {}        # hint -> next index, outside scopes

    def sequence_number(self, hint):
        """Next per-hint index at the current nesting level."""
        counts = self.frames[-1].counts if self.frames \
            else self.top_counts
        idx = counts.get(hint, 0)
        counts[hint] = idx + 1
        return idx

    def owner(self):
        """The block whose ``name_scope`` is innermost, or None."""
        return self.frames[-1].block if self.frames else None


_NAMING = _NamingState()


class _Frame(object):
    """One block's naming frame: its per-hint child counters. Pushed on
    the thread's frame stack for the duration of ``name_scope``."""

    __slots__ = ("block", "counts")

    def __init__(self, block):
        self.block = block
        self.counts = {}


def _derive_identity(prefix, params, hint):
    """Resolve a new Block's (full_prefix, ParameterDict) from the
    enclosing ``name_scope``, its constructor arguments, and the
    auto-naming contract (see _NamingState)."""
    # identity checks throughout: container blocks define __len__, so
    # an empty Sequential is falsy yet very much an owner
    owner = _NAMING.owner()
    if prefix is None:
        prefix = "%s%d_" % (hint, _NAMING.sequence_number(hint))
    full_prefix = prefix if owner is None else owner.prefix + prefix
    if params is not None:
        # explicit sharing: reuse the donor dict's names verbatim
        pdict = ParameterDict(params.prefix, params)
    elif owner is not None:
        # child dict: named under the parent, sharing the parent's pool
        parent = owner.params
        pdict = ParameterDict(parent.prefix + prefix, parent._shared)
    else:
        pdict = ParameterDict(full_prefix)
    return full_prefix, pdict


def _flatten(args, fmt_name):
    """Flatten a nested list/tuple structure of NDArrays/Symbols into a
    flat list plus a structure spec (0 = one array, -1 = a None slot,
    list = nesting) that ``_regroup`` inverts (the reference's
    _flatten/_regroup contract, gluon/block.py:53)."""
    flat = []

    def walk(node):
        if isinstance(node, (nd.NDArray, _symbol.Symbol)):
            flat.append(node)
            return 0
        if node is None:
            flat.append(None)
            return -1
        if isinstance(node, (list, tuple)):
            return [walk(item) for item in node]
        raise ValueError(
            "When hybridized, the input of HybridBlock %s must be "
            "(nested) list of Symbol or NDArray, but got %s of type %s"
            % (fmt_name, node, type(node)))

    spec = walk(args)
    return flat, spec


def _regroup(args, fmt):
    """Rebuild the nested structure described by ``fmt`` from the flat
    ``args`` list; returns (structure, leftover_args)."""
    def take(spec, pos):
        if spec == -1:
            return None, pos + 1
        if spec == 0:
            return args[pos], pos + 1
        if isinstance(spec, int):
            return args[pos:pos + spec], pos + spec
        out = []
        for sub in spec:
            item, pos = take(sub, pos)
            out.append(item)
        return out, pos

    structure, used = take(fmt, 0)
    return structure, args[used:]


class Block(object):
    """Base class for all neural network layers and models
    (python/mxnet/gluon/block.py:131).

    Childs and Parameters set as attributes are registered automatically;
    ``collect_params()`` returns the full ParameterDict of the subtree.
    """

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _derive_identity(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._frame = _Frame(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def __repr__(self):
        children = [(attr, val) for attr, val in self.__dict__.items()
                    if isinstance(val, Block)]
        body = "\n".join("  (%s): %s" % (attr, repr(val).replace(
            "\n", "\n  ")) for attr, val in children)
        return "%s(\n%s\n)" % (type(self).__name__, body)

    def __setattr__(self, name, value):
        prev = getattr(self, name, None)
        if isinstance(prev, (Parameter, Block)):
            # re-binding a registered attribute must keep its kind:
            # related types are fine (subclass either way), a kind
            # switch is a user error
            related = isinstance(value, type(prev)) \
                or isinstance(prev, type(value))
            if not related:
                raise TypeError(
                    "Changing attribute type for %s from %s to %s is not "
                    "allowed." % (name, type(prev), type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            taken = self._reg_params.get(name)
            assert taken is None or taken is value, \
                "Overriding Parameter attribute %s is not allowed. " \
                "If you want to share parameters between blocks, please " \
                "set 'params' at Block construction instead." % name
            self._reg_params[name] = value
        super(Block, self).__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    # ---------------------------------------------------------- naming --
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @contextlib.contextmanager
    def name_scope(self):
        """Context manager under which children and symbols are named
        as descendants of this block. Each entry pushes this block's
        naming frame (child indices persist across re-entries, so
        ``with net.name_scope()`` twice keeps counting where it left
        off) and routes op naming through a ``Prefix`` manager; an
        empty-prefix block scopes nothing."""
        if self._empty_prefix:
            yield
            return
        _NAMING.frames.append(self._frame)
        try:
            with _name.Prefix(self._prefix):
                yield
        finally:
            _NAMING.frames.pop()

    @property
    def params(self):
        """Returns this Block's parameter dictionary (does not include its
        children's parameters)."""
        return self._params

    def _subtree(self):
        """Pre-order iterator over this block and every descendant."""
        yield self
        for child in self._children.values():
            yield from child._subtree()

    def collect_params(self, select=None):
        """Returns a ParameterDict containing this Block's and all of its
        children's Parameters, optionally filtered by regex ``select``."""
        keep = re.compile(select).match if select else None
        ret = ParameterDict(self._params.prefix)
        for blk in self._subtree():
            chosen = blk.params.items() if keep is None else \
                ((n, p) for n, p in blk.params.items() if keep(n))
            ret.update(dict(chosen))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        """{structural dotted path: Parameter} over the subtree — the
        naming scheme save_parameters/load_parameters share."""
        found = {}
        todo = [(prefix, self)]
        while todo:
            path, blk = todo.pop()
            dot = path + "." if path else ""
            found.update((dot + key, val)
                         for key, val in blk._reg_params.items())
            todo.extend(reversed([(dot + name, child)
                                  for name, child in
                                  blk._children.items()]))
        return found

    # ---------------------------------------------------------- children --
    def register_child(self, block, name=None):
        key = str(len(self._children)) if name is None else name
        self._children[key] = block

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def apply(self, fn):
        """Applies ``fn`` to every block in the subtree, children before
        parents (post-order)."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    # -------------------------------------------------------------- io --
    def save_parameters(self, filename, deduplicate=False):
        """Saves parameters to file using structural naming
        (gluon/block.py:319)."""
        def fetch(param):
            reduce_fn = getattr(param, "_reduce", None)
            return reduce_fn() if reduce_fn is not None else param.data()
        nd.save(filename, {key: fetch(val) for key, val in
                           self._collect_params_with_prefix().items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Loads parameters from file (gluon/block.py:361). Accepts both
        structural-name files (save_parameters) and full-name files
        (collect_params().save)."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        structural = any("." in key for key in loaded)
        if not structural:
            # full parameter names — a legacy collect_params().save file
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source)
            return
        missing = [n for n in params if n not in loaded]
        assert allow_missing or not missing, \
            "Parameter '%s' is missing in file '%s'" % \
            (missing[0] if missing else "", filename)
        for name, value in loaded.items():
            target = params.get(name)
            if target is None:
                assert ignore_extra, \
                    "Parameter '%s' loaded from file '%s' is not present " \
                    "in this block" % (name, filename)
                continue
            target._load_init(value, ctx, cast_dtype=cast_dtype,
                              dtype_source=dtype_source)

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------- init --
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """Initializes Parameters of this Block and its children."""
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """Activates or deactivates HybridBlock children recursively."""
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        """Cast every parameter in the subtree (post-order, matching
        apply())."""
        for child in self._children.values():
            child.cast(dtype)
        for param in self.params.values():
            param.cast(dtype)

    # ------------------------------------------------------------- call --
    def __call__(self, *args):
        # step-phase telemetry: ONE "forward" span per outermost block
        # call (children nest inside it, per-layer spans would drown
        # the ring); depth tracked per thread
        depth = getattr(_CALL_DEPTH, "v", 0)
        fwd_span = None
        if depth == 0 and _obs.enabled():
            fwd_span = _obs.span("forward", cat="step",
                                 block=self._name or
                                 type(self).__name__).start()
        _CALL_DEPTH.v = depth + 1
        try:
            for hook in self._forward_pre_hooks:
                hook(self, args)
            if self._name and _obs_attr.ops_enabled():
                # per-operator attribution: any jax trace happening
                # inside forward (a hybridized child compiling, an
                # eager op jitting) carries this block's name as an
                # op_name scope component. One guarded branch when off.
                import jax
                _obs_attr.note_scope(self._name)
                with jax.named_scope(self._name):
                    out = self.forward(*args)
            else:
                out = self.forward(*args)
        finally:
            _CALL_DEPTH.v = depth
            if fwd_span is not None:
                fwd_span.stop()
        for hook in self._forward_hooks:
            hook(self, args, out)
        from ..util import is_np_array
        if is_np_array():
            # numpy-array semantics (util.set_np/use_np): emit
            # mx.np.ndarray wrappers over the same buffers
            from .. import numpy as _mxnp
            from ..ndarray import NDArray as _ND

            def _wrap(o):
                if isinstance(o, _ND) and not isinstance(o, _mxnp.ndarray):
                    return _mxnp.array(o._data)
                if isinstance(o, (list, tuple)):
                    return type(o)(_wrap(x) for x in o)
                return o
            out = _wrap(out)
        return out

    def forward(self, *args):
        """Overridden by users: imperative computation over NDArray."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a table of layer outputs and params for given inputs."""
        summary = []
        hooks = []

        def _register(block):
            def hook(blk, ins, outs):
                n_params = sum(
                    int(p.data().size) for p in blk.params.values()
                    if p._data is not None)
                first = outs[0] if isinstance(outs, (list, tuple)) else outs
                summary.append((blk.name, type(blk).__name__,
                                getattr(first, "shape", None), n_params))
            hooks.append(block.register_forward_hook(hook))

        self.apply(_register)
        try:
            self(*inputs)
            lines = ["%-30s %-20s %-20s %10s" %
                     ("Layer (name)", "Type", "Output Shape", "Params")]
            lines.append("-" * 84)
            total = 0
            for name, tname, shape, n in summary:
                total += n
                lines.append("%-30s %-20s %-20s %10d"
                             % (name, tname, str(shape), n))
            lines.append("-" * 84)
            lines.append("Total params: %d" % total)
            print("\n".join(lines))
        finally:
            def _clean(blk):
                blk._forward_hooks = [h for h in blk._forward_hooks
                                      if h not in hooks]
            self.apply(_clean)


class HybridBlock(Block):
    """A Block that supports hybridization: forwarding with NDArray or
    Symbol, and compilation of the traced graph via CachedOp
    (python/mxnet/gluon/block.py:705)."""

    def __init__(self, prefix=None, params=None):
        super(HybridBlock, self).__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = []
        self._clear_cached_op()

    def __setattr__(self, name, value):
        super(HybridBlock, self).__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s. If you are using Sequential, please try "
                "HybridSequential instead." % (str(block), str(type(block))))
        super(HybridBlock, self).register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = list(kwargs.items())
        self._clear_cached_op()
        super(HybridBlock, self).hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super(HybridBlock, self).cast(dtype)

    def _clear_cached_op(self):
        self._cached_graph = ()
        self._cached_op = None
        self._cached_op_args = []   # (is_data, slot-or-Parameter) pairs

    # ------------------------------------------------------------ trace --
    def _get_graph(self, *args):
        if not self._cached_graph:
            flat_args, self._in_format = _flatten(args, "input")
            real = [a for a in flat_args if a is not None]
            if len(real) == 1:
                syms = [_symbol.var("data")]
            else:
                syms = [_symbol.var("data%d" % i) for i in range(len(real))]
            it = iter(syms)
            grouped = [next(it) if a is not None else None for a in flat_args]
            grouped_args, _ = _regroup(grouped, self._in_format)
            if not isinstance(grouped_args, (list, tuple)):
                grouped_args = [grouped_args]
            params = {name: p.var() for name, p in self._reg_params.items()}
            with self.name_scope():
                out = self.hybrid_forward(_symbol, *grouped_args, **params)
            flat_out, self._out_format = _flatten(out, "output")
            if len(flat_out) > 1:
                self._cached_graph = (syms, _symbol.Group(flat_out))
            else:
                self._cached_graph = (syms, flat_out[0])
        return self._cached_graph

    def infer_shape(self, *args):
        """Infers shape of Parameters from inputs."""
        self._deferred_infer_shape(*args)

    def infer_type(self, *args):
        """Infers dtype of Parameters from inputs (reference
        HybridBlock.infer_type). Parameters follow the input dtype —
        under the bf16 AMP policy a float16/bfloat16 example input casts
        the float parameters accordingly."""
        flat_args, _ = _flatten(args, "input")
        real = [a for a in flat_args if a is not None]
        if not real:
            return
        dtype = real[0].dtype
        import numpy as _np
        if _np.dtype(dtype).kind != "f":
            return
        for param in self.collect_params().values():
            if param._data is not None and \
                    _np.dtype(param.dtype).kind == "f":
                param.cast(dtype)

    def _deferred_infer_shape(self, *args):
        import numpy as _np
        try:
            inputs, out = self._get_graph(*args)
            flat_args, _ = _flatten(args, "input")
            real = [a for a in flat_args if a is not None]
            # stamp the REAL input dtypes onto the data vars: the
            # graph walk evaluates ops dtype-aware, and a cast()
            # network (bf16 weights) fed by a default-fp32 data var
            # hits mixed-dtype eval errors mid-graph, silently
            # stranding every later parameter shape as unknown
            for i, a in zip(inputs, real):
                i._set_attr(__dtype__=str(_np.dtype(a.dtype)))
            kwargs = {i.name: a.shape for i, a in zip(inputs, real)}
            arg_shapes, _, aux_shapes = out.infer_shape_partial(**kwargs)
            sdict = dict(zip(out.list_arguments(), arg_shapes))
            sdict.update(zip(out.list_auxiliary_states(), aux_shapes))
            for name, param in self.collect_params().items():
                shp = sdict.get(name)
                if shp is not None:
                    param.shape = shp
        except Exception as e:
            raise ValueError(
                "Deferred initialization failed because shape cannot be "
                "inferred: %s" % e)

    # ------------------------------------------------------------ cache --
    def _build_cache(self, *args):
        inputs, out = self._get_graph(*args)
        by_name = {p.name: p for p in self.collect_params().values()}
        slot_of = {sym.name: idx for idx, sym in enumerate(inputs)}
        plan = []
        for name in out.list_inputs():
            if name in slot_of:
                plan.append((True, slot_of[name]))
            elif name in by_name:
                plan.append((False, by_name[name]))
            else:
                raise AssertionError(
                    "Unknown input to HybridBlock: %s" % name)
        self._cached_op_args = plan
        self._cached_op = CachedOp(out, self._flags)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._build_cache(*args)
        real = [a for a in _flatten(args, "input")[0] if a is not None]
        # arg structure changed since the trace (e.g. an RNN layer called
        # with and without explicit begin_state) -> retrace
        n_traced = sum(1 for is_data, _ in self._cached_op_args if is_data)
        if n_traced != len(real):
            self._clear_cached_op()
            self._build_cache(*args)
        out = self._cached_op(*[
            real[slot] if is_data else slot.data()
            for is_data, slot in self._cached_op_args])
        if len(out) == 1 and self._out_format == 0:
            return out[0]
        return _regroup(list(out), self._out_format)[0]

    # ---------------------------------------------------------- forward --
    def _materialize_params(self, x, *args):
        """Live param arrays for hybrid_forward; on a deferred init,
        infer shapes from the inputs, finish initialization, retry."""
        try:
            return {name: p.data()
                    for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            for p in self.collect_params().values():
                p._finish_deferred_init()
            return {name: p.data()
                    for name, p in self._reg_params.items()}

    def forward(self, x, *args):
        """Defines the forward computation; dispatches to
        ``hybrid_forward`` with F=ndarray or F=symbol."""
        if isinstance(x, _symbol.Symbol):
            params = {name: p.var()
                      for name, p in self._reg_params.items()}
            with self.name_scope():
                return self.hybrid_forward(_symbol, x, *args, **params)
        if not isinstance(x, nd.NDArray):
            raise AssertionError(
                "HybridBlock requires the first argument to forward be "
                "either Symbol or NDArray, but got %s" % type(x))
        if self._active:
            try:
                return self._call_cached_op(x, *args)
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for p in self.collect_params().values():
                    p._finish_deferred_init()
                return self._call_cached_op(x, *args)
        params = self._materialize_params(x, *args)
        return self.hybrid_forward(nd, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Overridden by users: computation over ``F`` (mx.nd or mx.sym)."""
        raise NotImplementedError

    # ------------------------------------------------------------ export --
    def export(self, path, epoch=0):
        """Exports traced symbol + params for deployment
        (gluon/block.py:907): path-symbol.json and path-NNNN.params."""
        if not self._cached_graph:
            raise RuntimeError(
                "Please first call block.hybridize() and then run forward "
                "with this block at least once before calling export.")
        sym = self._cached_graph[1]
        sym.save("%s-symbol.json" % path)

        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        arg_dict = {}
        for name, param in self.collect_params().items():
            if name in arg_names:
                arg_dict["arg:%s" % name] = param.data()
            elif name in aux_names:
                arg_dict["aux:%s" % name] = param.data()
        nd.save("%s-%04d.params" % (path, epoch), arg_dict)
        return sym


class SymbolBlock(HybridBlock):
    """Construct a Block from a Symbol (gluon/block.py:992) — the importer
    for exported models."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        names = [input_names] if isinstance(input_names, str) \
            else input_names
        block = SymbolBlock(_symbol.load(symbol_file),
                            [_symbol.var(n) for n in names])
        if param_file is not None:
            block.collect_params().load(
                param_file, ctx=ctx, cast_dtype=True,
                dtype_source="saved", allow_missing=False,
                ignore_extra=False)
        return block

    def __init__(self, outputs, inputs, params=None):
        super(SymbolBlock, self).__init__(prefix=None, params=None)
        self._prefix = ""
        self._params = ParameterDict("", params)
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = _symbol.Group(outputs)
        if isinstance(inputs, _symbol.Symbol):
            inputs = [inputs]

        syms, self._in_format = _flatten(inputs, "input")
        out = outputs
        input_names = set(s.name for s in syms)

        for name in out.list_arguments():
            if name not in input_names:
                p = self._params.get(name, allow_deferred_init=True)
                self._reg_params[name] = p
        for name in out.list_auxiliary_states():
            if name not in input_names:
                p = self._params.get(name, grad_req="null",
                                     allow_deferred_init=True)
                self._reg_params[name] = p

        self._cached_graph = syms, out
        self._build_cache_from_graph()

    def _build_cache_from_graph(self):
        inputs, out = self._cached_graph
        by_name = {p.name: p for p in self._params.values()}
        slot_of = {sym.name: idx for idx, sym in enumerate(inputs)}
        self._cached_op_args = [
            (True, slot_of[name]) if name in slot_of
            else (False, by_name[name]) for name in out.list_inputs()]
        self._cached_op = CachedOp(out, self._flags)
        self._out_format = _flatten(
            [out] if len(out.list_outputs()) == 1 else
            [out[i] for i in range(len(out.list_outputs()))], "output")[1]
        if len(out.list_outputs()) == 1:
            self._out_format = 0

    def forward(self, x, *args):
        if isinstance(x, _symbol.Symbol):
            return self._cached_graph[1]
        if not isinstance(x, nd.NDArray):
            raise AssertionError(
                "SymbolBlock requires Symbol or NDArray input")
        try:
            return self._call_cached_op(x, *args)
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            for p in self._params.values():
                p._finish_deferred_init()
            return self._call_cached_op(x, *args)

    def _call_cached_op(self, *args):
        real = [a for a in _flatten(args, "input")[0] if a is not None]
        out = self._cached_op(*[
            real[slot] if is_data else slot.data()
            for is_data, slot in self._cached_op_args])
        return out[0] if len(out) == 1 else list(out)

    def _clear_cached_op(self):
        tmp = getattr(self, "_cached_graph", ())
        super(SymbolBlock, self)._clear_cached_op()
        self._cached_graph = tmp

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
