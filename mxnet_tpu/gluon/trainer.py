"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py:27 (step:305,
_allreduce_grads:356, _update:399). Applies an Optimizer to a set of
Parameters; gradient aggregation across data-parallel devices goes through
the KVStore layer, which on this build is XLA collectives over the active
device mesh (the reference's engine-priority comm/compute overlap is
subsumed by XLA's async scheduling of collectives).
"""

from .. import optimizer as opt
from .. import kvstore as kvs
from ..base import MXNetError
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer(object):
    """Applies an Optimizer on a set of Parameters.

    Parameters
    ----------
    params : ParameterDict or list of Parameter
    optimizer : str or Optimizer
    optimizer_params : dict
    kvstore : str or KVStore, default 'device'
    compression_params : dict, optional (gradient compression config)
    update_on_kvstore : bool, optional
    """

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._param2idx[param.name] = i
            self._params.append(param)
            param._trainer = self
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states = {}

    def _init_optimizer(self, optimizer, optimizer_params):
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer, **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if isinstance(self._kvstore_type, kvs.KVStore):
            kv = self._kvstore_type
        elif self._kvstore_type is None:
            kv = None
        else:
            kv = kvs.create(self._kvstore_type)
        self._kvstore = kv
        if self._update_on_kvstore is None:
            self._update_on_kvstore = False
        if kv is not None:
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                if param._data is not None:
                    kv.init(i, param.data())
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @learning_rate.setter
    def learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------- step --
    def step(self, batch_size, ignore_stale_grad=False):
        """Makes one parameter update step: rescale grads by 1/batch_size,
        allreduce across data-parallel replicas, apply optimizer
        (gluon/trainer.py:305)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        # AMP fp16 dynamic loss scaling (contrib.amp.init_trainer): check
        # overflow, fold 1/scale into the update, skip the step when any
        # grad is non-finite
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            skip = scaler.has_overflow(self._params)
            scaler.update_scale(skip)
            if skip:
                return
            self._optimizer.rescale_grad /= scaler.loss_scale
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(i, param.grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore.pull(i, param.grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "Parameter %s has not been initialized" % param.name)
                continue
            if not getattr(param._data, "_fresh_grad", False):
                # grad array still holds a previous iteration's value
                # (reference: trainer.py _update fresh-grad check)
                if ignore_stale_grad:
                    continue
                raise UserWarning(
                    "Gradient of Parameter `%s` on context %s has not been "
                    "updated by backward since last `step`. This could mean "
                    "a bug in your model that made it only use a subset of "
                    "the Parameters (Blocks) for this iteration. If you are "
                    "intentionally only using a subset, call step with "
                    "ignore_stale_grad=True to suppress this warning"
                    % (param.name, str(param.list_ctx()[0])))
            if self._update_on_kvstore and self._kvstore is not None:
                self._kvstore.pull(i, param.data(), priority=-i)
            else:
                self._updaters[0](i, param.grad(), param.data())
            param._data._fresh_grad = False

    # ------------------------------------------------------------ states --
    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        self._updaters[0].set_states(states)
