"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py:27 (step:305,
_allreduce_grads:356, _update:399). Applies an Optimizer to a set of
Parameters; gradient aggregation across data-parallel devices goes through
the KVStore layer, which on this build is XLA collectives over the active
device mesh.

Comm path: by default gradients travel BUCKETED (parallel/fusion.py) —
keys pack into ~25 MB buckets in reverse-registration order (the last
layers' grads, ready first in backward, reduce first — the reference's
priority push, trainer.py:356 priority=-idx) and each bucket is one
fused collective dispatch; XLA's async dispatch overlaps a bucket's
all-reduce with the packing of the next. MXNET_KVSTORE_FUSION=0
restores the per-key path. MXNET_KVSTORE_SHARD_UPDATE=1 additionally
moves the optimizer into the store as a reduce-scatter -> sharded
update -> all-gather per bucket (PAPERS.md cross-replica sharding),
which cuts per-replica optimizer state by (N-1)/N.
"""

import time as _time

from .. import optimizer as opt
from .. import kvstore as kvs
from ..base import MXNetError
from ..observability import chaos as _chaos
from ..observability import core as _obs
from ..observability import dist as _obs_dist
from ..observability import goodput as _obs_goodput
from ..observability import integrity as _integrity
from ..observability import membudget as _membudget
from ..observability import recompile as _obs_recompile
from ..parallel import elastic as _elastic
from ..parallel import fusion
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer(object):
    """Applies an Optimizer on a set of Parameters.

    Parameters
    ----------
    params : ParameterDict or list of Parameter
    optimizer : str or Optimizer
    optimizer_params : dict
    kvstore : str or KVStore, default 'device'
    compression_params : dict, optional (gradient compression config)
    update_on_kvstore : bool, optional
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        seq = list(params.values()) if hasattr(params, "values") \
            else params
        if not isinstance(seq, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        outsider = next(
            (p for p in seq if not isinstance(p, Parameter)), None)
        if outsider is not None:
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got list of %s." % (type(outsider)))
        self._params = list(seq)
        self._param2idx = {p.name: i
                           for i, p in enumerate(self._params)}
        for p in self._params:
            p._trainer = self
        self._compression_params = compression_params
        hyper = dict(optimizer_params or {})
        self._scale = float(hyper.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, hyper)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states = {}

    def _init_optimizer(self, optimizer, hyper):
        ready_made = isinstance(optimizer, opt.Optimizer)
        assert not (ready_made and hyper), \
            "optimizer_params must be None if optimizer is an " \
            "Optimizer instance"
        self._optimizer = optimizer if ready_made \
            else opt.create(optimizer, **hyper)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _resolve_store(self):
        spec = self._kvstore_type
        if spec is None or isinstance(spec, kvs.KVStore):
            return spec
        return kvs.create(spec)

    def _init_kvstore(self):
        kv = self._kvstore = self._resolve_store()
        if self._update_on_kvstore is None:
            # the sharded weight update runs INSIDE the store (its
            # reduce-scatter -> sharded-update -> all-gather program
            # owns the optimizer state), so requesting it flips the
            # update onto the kvstore; every other config updates
            # locally as before
            self._update_on_kvstore = bool(
                kv is not None
                and fusion.shard_update_enabled()
                and kv.supports_shard_update()
                and fusion.FlatOptimizer.supports(self._optimizer)
                is not None)
        if kv is not None:
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            for slot, param in enumerate(self._params):
                if param._data is not None:
                    kv.init(slot, param.data())
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    def _ready(self):
        """Lazy kvstore bring-up shared by every entry point."""
        if not self._kv_initialized:
            self._init_kvstore()

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @learning_rate.setter
    def learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------- step --
    def step(self, batch_size, ignore_stale_grad=False):
        """Makes one parameter update step: rescale grads by 1/batch_size,
        allreduce across data-parallel replicas, apply optimizer
        (gluon/trainer.py:305)."""
        self._ready()
        _t_step_ns = _time.perf_counter_ns() if _obs.enabled() else None
        try:
            with _obs.span("trainer.step", cat="step"):
                self._optimizer.rescale_grad = self._scale / batch_size
                if _chaos.enabled():
                    # chaos site: an "oom" rule raises a real-shaped
                    # RESOURCE_EXHAUSTED here — the membudget taxonomy
                    # and recovery paths' replayable prey
                    _chaos.fire("trainer.step")
                    # a "nan" rule poisons this step's local gradients
                    # — the fault the step guard below exists for
                    _chaos.poison_ndarrays(
                        "trainer.grads",
                        [p.grad() for _, p in self._trainable()
                         if p._data is not None])
                    # silent weight corruption on this rank — the
                    # integrity cross-rank vote's prey
                    _chaos.poison_bitflip(
                        "trainer.weights",
                        [p.data() for _, p in self._trainable()
                         if p._data is not None])
                if _chaos.step_guard_enabled() \
                        and not self._grads_finite():
                    # non-finite loss/grads: skip allreduce AND update
                    # (the update may live inside the store), back off
                    # the AMP loss scale when one rides the trainer,
                    # and count the skip — one bad batch must never
                    # poison the weights
                    _chaos.count_skipped_step(
                        "trainer",
                        getattr(self, "_amp_loss_scaler", None))
                    return
                self._allreduce_grads()
                # AMP fp16 dynamic loss scaling
                # (contrib.amp.init_trainer): check overflow, fold
                # 1/scale into the update, skip the step when any grad
                # is non-finite
                scaler = getattr(self, "_amp_loss_scaler", None)
                if scaler is not None:
                    skip = scaler.has_overflow(self._params)
                    scaler.update_scale(skip)
                    if skip:
                        return
                    self._optimizer.rescale_grad /= scaler.loss_scale
                self._update(ignore_stale_grad)
        except Exception as exc:
            # OOM taxonomy: classify a RESOURCE_EXHAUSTED (and, under
            # MXNET_MEM_OOM_ACTION=checkpoint, route through the
            # emergency provider + exit 47 for the supervisor). A
            # non-OOM error — or an unarmed run — re-raises untouched.
            _membudget.handle_trainer_oom(exc)
            raise
        if _obs.enabled():
            # bounded-memory step-time distribution (p99 over the whole
            # run, not the ring suffix); per-rank histograms merge
            # bucket-wise in merged traces
            if _t_step_ns is not None:
                _obs.histogram("trainer.step_ms", "ms").observe(
                    (_time.perf_counter_ns() - _t_step_ns) / 1e6)
            # arm the recompile detector once the step's graphs exist,
            # and (multi-worker, every MXNET_OBS_SKEW_EVERY steps) run
            # the cross-rank straggler exchange
            _obs_recompile.step_boundary()
            _obs_dist.step_boundary(self._kvstore)
            # goodput ledger: this step committed (skip paths returned
            # above) — count it and, once per elastic generation, write
            # the first-commit sideband record that closes the
            # recovery interval (goodput.elastic_downtime)
            _obs_goodput.note_step_commit(
                getattr(self, "_elastic_steps", None))
            # step-cadence mem.device.* gauge refresh (no-op unless
            # MXNET_MEM_GAUGE_EVERY is set) — headroom-driven brownout
            # and routing act on live data, not dump-time snapshots
            from .. import storage as _storage
            _storage.maybe_publish_device_memory_gauges()
        if _elastic.enabled():
            # elastic membership: heartbeat + dead-peer check at the
            # step boundary (the fast path — a peer detected here
            # shrinks BEFORE the next collective can wedge this rank)
            self._elastic_steps = getattr(self, "_elastic_steps", 0) + 1
            _elastic.step_boundary(self._elastic_steps)
        if _integrity.enabled():
            # silent-corruption detectors: replay-audit the lanes
            # recorded during this step's fused all-reduce and, on
            # cadence, run the cross-rank parameter fingerprint vote
            _integrity.step_boundary(self._integrity_items(),
                                     kv=self._kvstore)

    def allreduce_grads(self):
        self._ready()
        self._allreduce_grads()

    def _grads_finite(self):
        """Device-side finiteness verdict over this step's gradients
        (one scalar sync). Only consulted when MXNET_STEP_GUARD=1."""
        return _chaos.all_finite(
            [p.grad()._data for _, p in self._trainable()
             if p._data is not None])

    def _trainable(self):
        """(kvstore slot, param) for every param that receives grads."""
        return ((slot, p) for slot, p in enumerate(self._params)
                if p.grad_req != "null")

    def _integrity_items(self):
        """(slot, weight jax array) in the same reverse-registration
        order the fused gradient path uses, so vote evidence names the
        same bucket/lane a corrupt gradient would ride."""
        items = [(slot, p.data()._data) for slot, p in self._trainable()
                 if p._data is not None]
        items.reverse()
        return items

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        with _obs.span("allreduce", cat="step",
                       fused=fusion.fusion_enabled()):
            self._allreduce_grads_impl()

    def _allreduce_grads_impl(self):
        if fusion.fusion_enabled():
            items = [(slot, p) for slot, p in self._trainable()
                     if p._data is not None]
            if not items:
                return
            # reverse-registration (priority) order: backward produces
            # the LAST layers' gradients first, so their bucket's
            # collective dispatches first and overlaps the rest
            items.reverse()
            keys = [slot for slot, _ in items]
            grads = [p.grad() for _, p in items]
            self._kvstore.pushpull_fused(
                keys, grads,
                out=None if self._update_on_kvstore else grads)
            return
        for slot, param in self._trainable():
            self._kvstore.push(slot, param.grad(), priority=-slot)
            if not self._update_on_kvstore:
                self._kvstore.pull(slot, param.grad(), priority=-slot)

    def update(self, batch_size, ignore_stale_grad=False):
        self._ready()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        with _obs.span("update", cat="step",
                       on_kvstore=bool(self._update_on_kvstore)):
            self._update_impl(ignore_stale_grad)

    def _update_impl(self, ignore_stale_grad=False):
        for i, param in self._trainable():
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "Parameter %s has not been initialized" % param.name)
                continue
            if not getattr(param._data, "_fresh_grad", False):
                # grad array still holds a previous iteration's value
                # (reference: trainer.py _update fresh-grad check)
                if ignore_stale_grad:
                    continue
                raise UserWarning(
                    "Gradient of Parameter `%s` on context %s has not been "
                    "updated by backward since last `step`. This could mean "
                    "a bug in your model that made it only use a subset of "
                    "the Parameters (Blocks) for this iteration. If you are "
                    "intentionally only using a subset, call step with "
                    "ignore_stale_grad=True to suppress this warning"
                    % (param.name, str(param.list_ctx()[0])))
            if self._update_on_kvstore and self._kvstore is not None:
                self._kvstore.pull(i, param.data(), priority=-i)
            else:
                self._updaters[0](i, param.grad(), param.data())
            param._data._fresh_grad = False

    # ------------------------------------------------------------ states --
    def save_states(self, fname):
        assert self._optimizer is not None
        self._ready()
        if self._update_on_kvstore and self._kvstore is not None:
            # the store owns the states (including sharded flat slots)
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        self._ready()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
