"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (Parameter, Constant,
ParameterDict; 1029 LoC). TPU-native notes: a Parameter holds ONE global
NDArray — multi-device placement is expressed by a jax.sharding
PartitionSpec on that array (set via ``Parameter.shard_spec``), not by
per-context copies, so ``list_data`` returns a single element. Deferred
initialization (shape inferred at first forward) is preserved.
"""

import re
import threading

import numpy as np

from .. import autograd
from .. import initializer
from .. import ndarray as nd
from ..base import MXNetError
from ..context import current_context, Context

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (nd.NDArray,)


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization
    (python/mxnet/gluon/parameter.py:36)."""
    pass


class Parameter(object):
    """A Container holding parameters (weights) of Blocks
    (python/mxnet/gluon/parameter.py:42).

    Parameters
    ----------
    name : str
    grad_req : {'write', 'add', 'null'}
    shape : tuple, elements may be 0/-1 (unknown, inferred at first forward)
    dtype : numpy dtype or str
    lr_mult / wd_mult : float
    init : Initializer
    allow_deferred_init : bool
    differentiable : bool
    stype / grad_stype : {'default', 'row_sparse', 'csr'}
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._deferred_init = ()
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        self._shape = tuple(shape) if shape is not None else None
        self.name = name
        self._dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._stype = stype
        self._grad_stype = grad_stype
        self.grad_req = grad_req
        # sharding annotation for multi-device (TPU extension): a
        # jax.sharding.PartitionSpec applied when a mesh is active
        self.shard_spec = None

    def __repr__(self):
        s = "Parameter {name} (shape={shape}, dtype={dtype})"
        return s.format(name=self.name, shape=self.shape, dtype=self.dtype)

    # ------------------------------------------------------- properties --
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError(
                "grad_req must be write/add/null, got %s" % req)
        effective = req if self._differentiable else "null"
        if effective == self._grad_req:
            return
        self._grad_req = effective
        # transitioning in/out of "null" (re)binds the grad buffer
        if effective == "null":
            self._grad = None
        elif self._data is not None:
            self._init_grad()

    @property
    def dtype(self):
        return self._dtype

    @dtype.setter
    def dtype(self, dtype):
        self.cast(dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is not None:
            # every previously-declared dim must either be a wildcard
            # (0/-1, deferred) or agree exactly
            mismatch = len(self._shape) != len(new_shape) or any(
                old not in (0, -1) and old != new
                for old, new in zip(self._shape, new_shape))
            if mismatch:
                raise AssertionError(
                    "Expected shape %s is incompatible with given shape "
                    "%s for Parameter %s"
                    % (str(new_shape), str(self._shape), self.name))
        self._shape = tuple(new_shape)

    @property
    def stype(self):
        return self._stype

    @property
    def grad_stype(self):
        return self._grad_stype

    # ---------------------------------------------------------- helpers --
    def _shape_known(self):
        return (self._shape is not None and len(self._shape) > 0 and
                all(s > 0 for s in self._shape))

    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter '%s' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of "
                "data through the network before accessing Parameters."
                % self.name)
        raise RuntimeError(
            "Parameter '%s' has not been initialized. Note that you should "
            "initialize parameters and create Trainer with Block.collect_params() "
            "instead of Block.params" % self.name)

    def _init_impl(self, data):
        self._data = data if isinstance(data, nd.NDArray) else nd.array(data)
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = nd.zeros(self._data.shape, dtype=self._data.dtype)
        self._data.attach_grad(self._grad_req)
        self._data._grad = self._grad

    def _init_spec_str(self, init):
        """The per-param initializer override serialized the way
        InitDesc attrs carry it (empty = use the default init)."""
        import json
        if init is None:
            return ""
        return json.dumps([init, {}]) if isinstance(init, str) \
            else init.dumps()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if not self._shape_known():
            raise MXNetError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape: %s." % (self.name, str(self._shape)))
        with autograd.pause():
            if data is None:
                data = nd.zeros(self._shape, dtype=self._dtype)
                desc = initializer.InitDesc(
                    self.name, {"__init__": self._init_spec_str(init)})
                initializer.create(default_init)(desc, data)
            self._init_impl(data)

    # -------------------------------------------------------------- API --
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Initialize parameter and gradient arrays
        (python/mxnet/gluon/parameter.py:337)."""
        if self._data is not None and not force_reinit:
            import warnings
            warnings.warn(
                "Parameter '%s' is already initialized, ignoring. "
                "Set force_reinit=True to re-initialize." % self.name)
            return
        self._data = self._grad = None
        pending = (init if init is not None else self.init, ctx,
                   default_init or initializer.Uniform(), None)
        if self._shape_known():
            self._deferred_init = pending
            self._finish_deferred_init()
        elif self._allow_deferred_init:
            self._deferred_init = pending
        else:
            raise ValueError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape: %s. Please specify in_units, in_channels, etc "
                "for `Block`s." % (self.name, str(self._shape)))

    def _load_init(self, data, ctx=None, cast_dtype=False, dtype_source="current"):
        """Initialize from loaded data (used by load_parameters)."""
        if cast_dtype and dtype_source == "current" and self._dtype is not None:
            data = data.astype(self._dtype)
        else:
            self._dtype = data.dtype
        if self._shape is not None and self._shape_known():
            if tuple(self.shape) != tuple(data.shape):
                raise AssertionError(
                    "Failed loading Parameter '%s' from saved params: shape "
                    "incompatible expected %s vs saved %s"
                    % (self.name, str(self.shape), str(data.shape)))
        else:
            self._shape = tuple(data.shape)
        self._deferred_init = ()
        self._init_impl(data)

    def set_data(self, data):
        """Sets this parameter's value on all contexts."""
        self.shape = data.shape
        if self._data is not None:
            self._data._data = data._data \
                if isinstance(data, nd.NDArray) else np.asarray(data)
            return
        if not self._deferred_init:
            raise AssertionError(
                "Parameter '%s' has not been initialized" % self.name)
        # stash the value into the pending init so the first forward
        # lands it instead of drawing from the initializer
        self._deferred_init = self._deferred_init[:3] + (data,)

    def data(self, ctx=None):
        """Returns a copy of this parameter on one context — here the single
        global (possibly sharded) array."""
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def row_sparse_data(self, row_id):
        """Rows `row_id` of a row_sparse parameter (reference
        parameter.py row_sparse_data; dense-backed here, so this is a
        gather of the requested rows)."""
        if self._stype != "row_sparse":
            raise RuntimeError(
                "Cannot return a copy of Parameter %s via row_sparse_data()"
                " because its storage type is %s" % (self.name, self._stype))
        self._check_initialized()
        from .. import ndarray as nd
        return nd.take(self._data, row_id)

    def list_row_sparse_data(self, row_id):
        return [self.row_sparse_data(row_id)]

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' because "
                "grad_req='null'" % self.name)
        self._check_initialized()
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        if self._grad is None:
            return
        self._grad._data = nd.zeros(self._grad.shape,
                                    dtype=self._grad.dtype)._data

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return [self._deferred_init[1] or current_context()]
        self._check_initialized()
        return [self._data.context]

    def reset_ctx(self, ctx):
        pass  # single global array; placement is via shard_spec

    def var(self):
        """Returns the symbol representing this parameter."""
        from .. import symbol
        if self._var is None:
            # only bake the shape into the variable once fully known —
            # partial shapes (zeros) would defeat deferred shape inference
            shape = self.shape if self._shape_known() else None
            self._var = symbol.var(self.name, shape=shape,
                                   dtype=self._dtype, lr_mult=self.lr_mult,
                                   wd_mult=self.wd_mult,
                                   init=self.init)
        return self._var

    def cast(self, dtype):
        self._dtype = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = self._data.astype(self._dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(self._dtype)
                self._data.attach_grad(self._grad_req)
                self._data._grad = self._grad


class Constant(Parameter):
    """A constant parameter for holding non-differentiable values
    (python/mxnet/gluon/parameter.py:653)."""

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(value)
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr[:] = value._data
        init_name = "Constant_{}_{}".format(name, id(self))
        initializer._INIT_REGISTRY[init_name.lower()] = Init
        super(Constant, self).__init__(
            name, grad_req="null", shape=value.shape, dtype=value.dtype,
            init=init_name)


class ParameterDict(object):
    """A dictionary managing a set of parameters
    (python/mxnet/gluon/parameter.py:703)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __repr__(self):
        s = "{name}(\n{content}\n)"
        name = self._prefix + " " if self._prefix else ""
        return s.format(
            name=name,
            content="\n".join("  " + repr(v) for v in self.values()))

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        found = self._params.get(name)
        if found is None and self._shared is not None:
            found = self._shared._params.get(name)
            if found is not None:
                self._params[name] = found   # adopt the shared param
        return found

    def get(self, name, **kwargs):
        """Retrieves or creates a ``Parameter`` named ``self.prefix+name``.
        Matches the reference's attribute-compatibility rule
        (gluon/parameter.py ParameterDict.get): existing attributes must be
        compatible with the requested ones, partial shapes unify."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            private = {"differentiable": "_differentiable",
                       "allow_deferred_init": "_allow_deferred_init"}
            for k, v in kwargs.items():
                if v is None:
                    continue
                attr = private.get(k, k)
                existing = getattr(param, attr, None)
                if k in private:
                    # construction-time flags: must simply agree
                    if existing != v:
                        raise AssertionError(
                            "Cannot retrieve Parameter '%s' because desired "
                            "attribute does not match with stored for "
                            "attribute '%s': desired '%s' vs stored '%s'."
                            % (name, k, str(v), str(existing)))
                    continue
                if existing is None:
                    setattr(param, k, v)
                    continue
                if k == "shape" and len(v) == len(existing):
                    # unify: 0/-1 dims are wildcards on either side
                    if all(sv in (0, -1) or ev in (0, -1) or sv == ev
                           for sv, ev in zip(v, existing)):
                        param._shape = tuple(
                            ev if sv in (0, -1) else sv
                            for sv, ev in zip(v, existing))
                        continue
                elif k == "init" or existing == v:
                    continue
                raise AssertionError(
                    "Cannot retrieve Parameter '%s' because desired "
                    "attribute does not match with stored for attribute "
                    "'%s': desired '%s' vs stored '%s'."
                    % (name, k, str(v), str(existing)))
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(
                    "No constant named '{}'. Please specify value if you want "
                    "to create a new constant.".format(name))
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            if not isinstance(param, Constant):
                raise TypeError("Parameter '{}' already exists but is not a "
                                "constant.".format(name))
        return param

    def update(self, other):
        """Copies all Parameters in ``other`` to self."""
        for k, v in other.items():
            mine = self._params.setdefault(k, v)
            if mine is not v:
                raise ValueError(
                    "Cannot update self with other because they have "
                    "different Parameters with the same name '%s'" % k)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        default = init or initializer.Uniform()
        for v in self.values():
            v.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        misnamed = next((p.name for p in self.values()
                         if not p.name.startswith(strip_prefix)), None)
        if misnamed is not None:
            raise ValueError(
                "Prefix '%s' is to be striped before saving, but "
                "Parameter's name '%s' does not start with it"
                % (strip_prefix, misnamed))
        nd.save(filename, {p.name[len(strip_prefix):]: p.data()
                           for p in self.values()})

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False,
             dtype_source="current"):
        lprefix = len(restore_prefix)
        if restore_prefix:
            stray = next((n for n in self.keys()
                          if not n.startswith(restore_prefix)), None)
            assert stray is None, \
                "restore_prefix is '%s' but Parameter name '%s' does " \
                "not start with it" % (restore_prefix, stray)
        arg_dict = {restore_prefix + k.split(":", 1)[-1]: v
                    for k, v in nd.load(filename).items()}
        if not allow_missing:
            absent = next((n for n in self.keys()
                           if n not in arg_dict), None)
            assert absent is None, \
                "Parameter '%s' is missing in file '%s'" \
                % (absent and absent[lprefix:], filename)
        for name, value in arg_dict.items():
            target = self._params.get(name)
            if target is None:
                assert ignore_extra, \
                    "Parameter '%s' loaded from file '%s' is not " \
                    "present in ParameterDict" % (name[lprefix:], filename)
                continue
            target._load_init(value, ctx, cast_dtype=cast_dtype,
                              dtype_source=dtype_source)
