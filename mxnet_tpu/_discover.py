"""Wedge-proof jax backend discovery.

A wedged TPU tunnel (the axon plugin's transport accepting TCP but never
completing claims) makes the FIRST ``jax.devices()`` /
``jax.default_backend()`` call in a process hang indefinitely — observed
for hours in round 2. Because jax's backend-init lock is process-wide,
the hang cannot be recovered in-process; the only safe pre-check is a
THROWAWAY subprocess probe with a timeout.

This module is the single implementation of that probe (VERDICT r2
item 2). Users: ``mxnet_tpu.context`` (lazy, before the library's first
device resolution), ``bench.py`` (fail-fast error JSON), ``tests/
conftest.py`` and ``__graft_entry__.py`` (platform pinning helpers).

Semantics of :func:`ensure_backend` — the one call sites use:

* backend already initialized           -> no-op (cheap).
* ``JAX_PLATFORMS`` set                 -> honored via ``jax.config``
  BEFORE init (plugin discovery overrides the env var — the conftest
  gotcha). A pure-``cpu`` pin skips the probe (CPU never wedges); a
  non-cpu pin (this machine exports ``JAX_PLATFORMS=axon`` globally)
  is still probed, because the pinned plugin is the one that hangs.
* otherwise                             -> subprocess probe with timeout
  (``MXNET_BACKEND_PROBE_TIMEOUT``, default 90 s). On failure, either
  pin the CPU platform with a warning (default) or raise
  ``MXNetError`` (``MXNET_ON_WEDGED_BACKEND=error``).

Probe results are cached in a temp file for a few minutes so a session
running many short processes (pytest, tools) pays the probe cost once.
Reference counterpart: none — the reference's CUDA runtime fails fast on
a dead driver; the tunnel-backed PJRT plugin is what makes this guard
necessary here.
"""

import os
import subprocess
import sys
import tempfile
import threading
import time
import warnings

_PROBE_OK_MARK = "MXTPU_PROBE_OK"
_PROBE_CODE = "import jax; jax.devices(); print('%s')" % _PROBE_OK_MARK

_lock = threading.RLock()
_state = {"checked": False}


def backends_initialized():
    """True if a jax backend is already live in this process, determined
    WITHOUT triggering plugin discovery (which is the call that hangs on
    a wedged tunnel). Unknown internals -> False (callers then pin a
    platform or probe, both safe)."""
    try:
        from jax._src import xla_bridge as _xb
        return bool(getattr(_xb, "_backends", None))
    except Exception:
        return False


def _cache_path():
    # a per-user PRIVATE directory, not bare /tmp: a predictable world-
    # writable path could be pre-created by another local user to poison
    # the verdict (and the sticky bit would stop us correcting it)
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    if base.startswith("~"):  # no resolvable home: fall back to a
        base = tempfile.gettempdir()  # per-uid name in tempdir
    d = os.path.join(base, "mxnet_tpu")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
    except OSError:
        d = tempfile.gettempdir()
    try:
        uid = os.getuid()
    except AttributeError:
        uid = "na"
    return os.path.join(d, "backend_probe_%s" % uid)


def _cache_key():
    # the probe outcome depends on which platforms the subprocess tries
    # to initialize: an 'ok' recorded under a cpu pin must never satisfy
    # an unpinned (or tpu-pinned) process
    return os.environ.get("JAX_PLATFORMS", "").strip() or "auto"


def _cached_probe_result(ok_ttl_s=600.0, dead_ttl_s=240.0):
    """Returns True/False from a recent probe under the SAME platform
    pin, or None when stale/absent/mismatched/disabled. A dead result
    expires faster so a recovered tunnel is noticed within minutes."""
    if os.environ.get("MXNET_BACKEND_PROBE_CACHE", "1") in ("0", "false"):
        return None
    path = _cache_path()
    try:
        with open(path) as f:
            key, _, verdict = f.read().strip().rpartition(":")
        age = time.time() - os.path.getmtime(path)
    except OSError:
        return None
    if key != _cache_key():
        return None
    if verdict == "ok" and age < ok_ttl_s:
        return True
    if verdict == "dead" and age < dead_ttl_s:
        return False
    return None


def _store_probe_result(alive):
    if os.environ.get("MXNET_BACKEND_PROBE_CACHE", "1") in ("0", "false"):
        return
    try:
        with open(_cache_path(), "w") as f:
            f.write("%s:%s" % (_cache_key(), "ok" if alive else "dead"))
    except OSError:
        pass


def probe_backend_alive(timeout_s=None, probe_code=None, use_cache=True):
    """Probe jax device discovery in a throwaway subprocess. True when
    discovery completes within the timeout, False when it hangs or dies.

    ``probe_code`` is injectable for tests (a fake hanging plugin is
    simulated by probing a script that sleeps past the timeout)."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("MXNET_BACKEND_PROBE_TIMEOUT", 90))
    if probe_code is None and \
            os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # a cpu pin never wedges — and the env-var pin would NOT reach
        # the probe subprocess's backend init anyway (the axon plugin
        # overrides JAX_PLATFORMS during jax import), so probing under
        # a cpu pin would falsely report dead. Single home for this
        # rule; bench.py and run_chip_queue call through it.
        return True
    if use_cache and probe_code is None:
        cached = _cached_probe_result()
        if cached is not None:
            return cached
    code = probe_code if probe_code is not None else _PROBE_CODE
    env = dict(os.environ)
    # the probe must see the same plugin set the parent would; but never
    # let a parent's pinned-cpu leak make the probe vacuous — a pinned
    # parent skips the probe entirely in ensure_backend().
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, env=env)
        alive = _PROBE_OK_MARK.encode() in r.stdout
    except (subprocess.TimeoutExpired, OSError):
        alive = False
    if probe_code is None:
        _store_probe_result(alive)
    return alive


def ensure_backend(timeout_s=None, probe_code=None):
    """Guard this process's first jax backend initialization; see module
    docstring for the decision table. Idempotent and cheap after the
    first call. Returns nothing; raises MXNetError only when
    ``MXNET_ON_WEDGED_BACKEND=error`` and the probe fails."""
    with _lock:
        if _state["checked"]:
            return
        if backends_initialized():
            _state["checked"] = True
            return
        import jax
        plat = os.environ.get("JAX_PLATFORMS", "").strip()
        if plat:
            # honor the env var before init: plugin registration
            # overrides JAX_PLATFORMS, so pin through jax.config
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:
                pass
            if all(p.strip() in ("cpu", "") for p in plat.split(",")):
                _state["checked"] = True
                return  # pure-CPU pin never wedges; skip the probe
            # a non-cpu pin (this machine exports JAX_PLATFORMS=axon
            # globally) still initializes the tunnel-backed plugin and
            # still hangs when it is wedged — fall through to the probe,
            # whose subprocess inherits the same pin.
        if os.environ.get("MXNET_BACKEND_PROBE", "1") in ("0", "false"):
            _state["checked"] = True
            return
        alive = probe_backend_alive(timeout_s=timeout_s,
                                    probe_code=probe_code)
        if not alive:
            msg = ("jax backend device discovery did not complete within "
                   "the probe timeout (wedged TPU tunnel?). ")
            if os.environ.get("MXNET_ON_WEDGED_BACKEND", "cpu") == "error":
                # deliberately NOT marking checked: no CPU pin was
                # applied, so a caller that catches this and retries
                # must hit the guard (and the fast dead-cache) again,
                # not fall through into the real hang
                from .base import MXNetError
                raise MXNetError(
                    msg + "MXNET_ON_WEDGED_BACKEND=error is set; not "
                    "falling back. Rerun when the accelerator is "
                    "reachable, or set JAX_PLATFORMS=cpu explicitly.")
            warnings.warn(
                msg + "Falling back to the CPU platform for this "
                "process. Set MXNET_ON_WEDGED_BACKEND=error to raise "
                "instead, or JAX_PLATFORMS to pin a platform.",
                RuntimeWarning, stacklevel=3)
            # belt and suspenders: the env var covers child processes
            # and jax versions without the config key; the config update
            # covers plugins that override the env var. If BOTH fail we
            # must not promise a fallback we didn't apply.
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                warnings.warn(
                    "could not pin jax_platforms=cpu via jax.config; "
                    "relying on the JAX_PLATFORMS env var only — if a "
                    "plugin overrides it, the next jax call may still "
                    "hang", RuntimeWarning, stacklevel=3)
        _state["checked"] = True


def _reset_for_tests():
    _state["checked"] = False


def pin_platform_from_env():
    """Make an explicit `JAX_PLATFORMS=cpu` request stick.

    The axon plugin rewrites JAX_PLATFORMS to "axon,cpu" during jax
    import, so env-only pinning silently re-enables the tunnel backend
    — and a wedged tunnel then hangs backend init. Call this before the
    first jax touch in scripts that honor the env var (benchmarks,
    tests outside conftest)."""
    import os
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
