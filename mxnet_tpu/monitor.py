"""Monitor — per-step output/param inspection.

Reference: python/mxnet/monitor.py (Monitor installs a callback on
executor outputs; C++ hook graph_executor.cc:185 SetMonitorCallback).

TPU note: under whole-graph jit there is no per-op callback point, so
the Monitor hooks the two boundaries that DO exist:

* Module executors (``install``) — bound args/aux/outputs are inspected
  at step boundaries (tic/toc), covering the reference's main use
  (norm/NaN watching) without de-fusing the compiled program;
* Gluon blocks (``install_block``) — a forward hook records every
  block's output NDArrays as they are produced, the analogue of the
  reference's per-executor monitor callback.

Collected stats additionally route through the observability gauge API
(``mxnet_tpu/observability``) when telemetry is on: scalar stats land
as ``monitor.<name>`` gauges, so chrome traces / aggregate tables /
Prometheus scrapes carry the watched values next to the step phases.
"""

import logging
import re
from math import sqrt

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray
from .observability import core as _obs

__all__ = ["Monitor"]


class Monitor(object):
    """monitor.py:34."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """returns |x|/size(x), async execution."""
                return nd.norm(x) / sqrt(x.size)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.blocks = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        stat = self.stat_func(array)
        self.queue.append((self.step, name, stat))
        if _obs.enabled() and isinstance(stat, NDArray) \
                and stat.size == 1:
            _obs.gauge("monitor.%s" % name).set(float(stat.asscalar()))

    def install(self, exe):
        """Hook an executor (monitor.py:87)."""
        self.exes.append(exe)

    def install_block(self, block):
        """Hook a Gluon block (and every child): a forward hook records
        each block's output arrays through stat_helper, named
        ``<block>_output<i>`` — the per-op monitor callback the
        reference installs on executors, at the block granularity that
        exists under whole-graph jit."""

        def hook(blk, _inputs, outputs):
            if not self.activated:
                return
            outs = outputs if isinstance(outputs, (list, tuple)) \
                else [outputs]
            for i, out in enumerate(outs):
                if isinstance(out, NDArray):
                    self.stat_helper(
                        "%s_output%d" % (blk._name or
                                         type(blk).__name__, i), out)

        handles = []
        for b in self._walk(block):
            handles.append(b.register_forward_hook(hook))
        self.blocks.append(block)
        return handles

    @staticmethod
    def _walk(block):
        yield block
        for child in getattr(block, "_children", {}).values():
            yield from Monitor._walk(child)

    def tic(self):
        """Start collecting for this step (monitor.py:96)."""
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    array.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Collect stats from bound arrays (monitor.py:106)."""
        if not self.activated:
            return []
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                self.stat_helper(name, array)
            for name, array in exe.aux_dict.items():
                self.stat_helper(name, array)
            for name, array in zip(exe._symbol.list_outputs(), exe.outputs):
                self.stat_helper(name, array)
        for block in self.blocks:
            # parameters of hooked blocks (hook already caught outputs)
            for pname, param in block.collect_params().items():
                if param._data is not None:
                    self.stat_helper(pname, param.data())
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """monitor.py:139."""
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: {:7d} {:30s} {:s}".format(n, k, v))
