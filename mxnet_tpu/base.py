"""Base helpers and exceptions.

Reference: python/mxnet/base.py (ctypes ABI plumbing, MXNetError, registry
helpers). Here there is no C ABI to cross for the frontend — the native core
is JAX/XLA — so this module keeps only the user-visible pieces: the exception
type, name mangling, and the op-registration glue used to synthesize the
`nd.*` / `sym.*` namespaces (reference: python/mxnet/base.py:580-647).
"""

import re

string_types = (str,)
numeric_types = (float, int)


class MXNetError(Exception):
    """Error raised by mxnet_tpu (reference: python/mxnet/base.py:75)."""


class NotSupportedForTPU(MXNetError):
    """Raised for reference features that cannot map to TPU/XLA semantics."""


def check_call(ret):  # kept for API compatibility with reference base.py
    if ret != 0:
        raise MXNetError("non-zero return")


_CAMEL_RE1 = re.compile("(.)([A-Z][a-z]+)")
_CAMEL_RE2 = re.compile("([a-z0-9])([A-Z])")


def camel_to_snake(name):
    s = _CAMEL_RE1.sub(r"\1_\2", name)
    return _CAMEL_RE2.sub(r"\1_\2", s).lower()


def classproperty(func):
    class _Prop:
        def __get__(self, obj, owner):
            return func(owner)
    return _Prop()
