"""Library info (reference: python/mxnet/libinfo.py find_lib_path /
__version__). There is no libmxnet.so — the 'library' is the python
package + the native pipeline extension when built."""

import os

__version__ = "0.1.0"
__all__ = ["find_lib_path", "find_include_path", "__version__"]


def find_lib_path():
    """Paths of native extensions shipped with the package (the
    RecordIO/image C++ pipeline), empty if none built."""
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    libs = []
    native = os.path.join(curr, "native")
    if os.path.isdir(native):
        libs += [os.path.join(native, f) for f in os.listdir(native)
                 if f.endswith(".so")]
    return libs


def find_include_path():
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    return os.path.join(curr, "native", "include")
