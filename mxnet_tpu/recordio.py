"""RecordIO — binary record container + indexed variant + image records.

Reference: python/mxnet/recordio.py:37,216,344 (MXRecordIO,
MXIndexedRecordIO, IRHeader/pack/unpack) over dmlc-core's C++ recordio
writer; src/io/image_recordio.h:110 (IRHeader layout).

TPU-native: this module owns the on-disk format (kMagic-delimited,
length+content, 4-byte aligned) in Python so record files stay
interchangeable with reference tooling; the hot paths — whole-file
index scans and batched scatter reads — dispatch to the native
library built from src/io/recordio_scan.cc (ctypes, GIL-released
thread pool) with a pure-Python fallback.
"""

import ctypes
import numbers
import os
import struct
import zlib
from collections import namedtuple

import numpy as np

from . import _fastenv
from .observability import chaos as _chaos

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "RecordCorrupt",
           "pack", "unpack", "pack_img", "unpack_img"]

_kMagic = 0xced7230a


class RecordCorrupt(IOError):
    """A record failed its integrity check (bad magic or CRC mismatch).

    Subclasses IOError so the io.py retry path (``io._retry_read``)
    treats it as transient first: a bit flipped in the page cache or by
    injected chaos recovers on re-read, while a flip ON DISK exhausts
    the retries and surfaces this error naming the file and record.
    """

    def __init__(self, path, record_index, detail=""):
        self.path = path
        self.record_index = record_index
        msg = "corrupt record %s in %s" % (record_index, path)
        if detail:
            msg += ": %s" % detail
        super().__init__(msg)


def _crc_enabled():
    """MXNET_RECORDIO_CRC: write + verify the per-record CRC sidecar
    (default on; 0 disables both). The sidecar keeps the .rec format
    interchange-compatible — reference tooling ignores it."""
    return str(_fastenv.get("MXNET_RECORDIO_CRC", "1")).lower() \
        not in ("0", "false", "off", "")


def _crc_path(uri):
    return str(uri) + ".crc"


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(rec):
    return (rec >> 29) & 7, rec & ((1 << 29) - 1)


class MXRecordIO(object):
    """Sequential record reader/writer (recordio.py:37).

    Format per record: uint32 magic | uint32 lrec (3-bit cflag, 29-bit
    len) | payload | pad to 4-byte boundary. cflag 0 = whole record;
    1/2/3 = begin/middle/end of a split record (records > 2^29 bytes).

    Integrity (MXNET_RECORDIO_CRC, default on): writers emit a
    ``<uri>.crc`` sidecar of offset -> crc32(payload); readers verify
    each record against it and the frame magic, raising
    ``RecordCorrupt(path, record_index)`` — an IOError, so the io.py
    retry path re-reads once before the error surfaces.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.fio = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.fio = open(self.uri, "wb")
            self.writable = True
            self._crc_entries = [] if _crc_enabled() else None
            self._crc = None
        elif self.flag == "r":
            self.fio = open(self.uri, "rb")
            self.writable = False
            self._crc_entries = None
            self._crc = self._load_crc()
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self._read_count = 0
        self._pending_index = None
        self.pid = os.getpid()

    def _load_crc(self):
        """offset -> crc32 of the logical payload, from the sidecar."""
        if not _crc_enabled() or not os.path.isfile(_crc_path(self.uri)):
            return None
        table = {}
        with open(_crc_path(self.uri)) as fin:
            for line in fin:
                parts = line.strip().split("\t")
                if len(parts) == 2:
                    table[int(parts[0])] = int(parts[1], 16)
        return table or None

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_mx_rec = type(self).__name__ == "MXRecordIO"
        if not is_mx_rec:
            raise RuntimeError("Only MXRecordIO is picklable.")
        d = dict(self.__dict__)
        d["fio"] = None
        d["pid"] = None
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        self.open()

    def _check_pid(self, allow_reset=False):
        # fork safety (recordio.py:107): child must reopen its own handle
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in a forked process")

    def close(self):
        if self.fio is not None and not self.fio.closed:
            if self.writable and self._crc_entries:
                with open(_crc_path(self.uri), "w") as fout:
                    for off, crc in self._crc_entries:
                        fout.write("%d\t%08x\n" % (off, crc))
            self.fio.close()
        self.fio = None
        self.pid = None

    @property
    def is_open(self):
        return self.fio is not None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid(allow_reset=False)
        if self._crc_entries is not None:
            self._crc_entries.append(
                (self.fio.tell(), zlib.crc32(buf) & 0xFFFFFFFF))
        self.fio.write(struct.pack("<II", _kMagic,
                                   _encode_lrec(0, len(buf))))
        self.fio.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.fio.write(b"\x00" * pad)

    def tell(self):
        return self.fio.tell()

    def read(self):
        assert not self.writable
        self._check_pid(allow_reset=True)
        index = self._pending_index if self._pending_index is not None \
            else self._read_count
        self._pending_index = None
        start = self.fio.tell()
        parts = []
        while True:
            head = self.fio.read(8)
            if len(head) < 8:
                if parts:
                    break
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _kMagic:
                raise RecordCorrupt(
                    self.uri, index,
                    "bad magic 0x%08x (want 0x%08x)" % (magic, _kMagic))
            cflag, length = _decode_lrec(lrec)
            data = self.fio.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.fio.read(pad)
            parts.append(data)
            if cflag in (0, 3):  # whole record or end-of-split
                break
        self._read_count += 1
        data = b"".join(parts)
        if _chaos.enabled():
            # in-memory bit flip AFTER the read: a retried read sees
            # the clean on-disk bytes (the transient-SDC scenario)
            data = _chaos.corrupt_bytes("recordio.read", data,
                                        path=self.uri, record=index)
        want = self._crc.get(start) if self._crc else None
        if want is not None:
            got = zlib.crc32(data) & 0xFFFFFFFF
            if got != want:
                # rewind so a retry re-reads the same record
                self.fio.seek(start)
                self._read_count -= 1
                raise RecordCorrupt(
                    self.uri, index,
                    "crc %08x != sidecar %08x" % (got, want))
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records keyed by an .idx file (recordio.py:216)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        self._native_lengths = None
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if self.fio is None:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def __getstate__(self):
        raise RuntimeError("MXIndexedRecordIO is not picklable.")

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        self.fio.seek(self.idx[idx])
        self._pending_index = idx  # name THIS key in corruption errors

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos

    def build_index(self, write=True):
        """(Re)build the key -> offset table by scanning the .rec file —
        covers files produced without an .idx sidecar. The scan runs in
        the native library (src/io/recordio_scan.cc) when available,
        falling back to a Python frame walk."""
        assert not self.writable, \
            "build_index requires read mode (close the writer first: its " \
            "buffered tail would be missing from the scan)"
        from . import _native
        scanned = _native.recordio_scan(self.uri)
        if scanned is not None:
            offsets = [int(o) for o in scanned[0]]
        else:
            offsets = []
            with open(self.uri, "rb") as f:
                pos = 0
                while True:
                    head = f.read(8)
                    if len(head) < 8:
                        break
                    magic, lrec = struct.unpack("<II", head)
                    if magic != _kMagic:
                        raise RecordCorrupt(
                            self.uri, len(offsets),
                            "bad magic 0x%08x during index scan" % magic)
                    cflag, length = _decode_lrec(lrec)
                    if cflag in (0, 1):       # logical record start
                        offsets.append(pos)
                    pos += 8 + length + (4 - length % 4) % 4
                    f.seek(pos)
        self.keys = [self.key_type(i) for i in range(len(offsets))]
        self.idx = dict(zip(self.keys, offsets))
        if write:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        return self.keys

    def read_batch(self, indices, num_threads=4):
        """Payloads of many records in one call. Uses the native
        scatter-reader thread pool when available; otherwise sequential
        read_idx calls."""
        assert not self.writable, \
            "read_batch requires read mode (close the writer and reopen)"
        from . import _native
        offsets = [self.idx[i] for i in indices]
        length_of = getattr(self, "_native_lengths", None)
        if length_of is None and _native.recordio_lib() is not None:
            scanned = _native.recordio_scan(self.uri)
            if scanned is not None:
                off_arr, len_arr = scanned
                length_of = dict(zip((int(o) for o in off_arr),
                                     (int(n) for n in len_arr)))
            self._native_lengths = length_of or {}
        if length_of:
            try:
                lengths = [length_of[o] for o in offsets]
            except KeyError:
                lengths = None
            if lengths is not None:
                out = _native.recordio_read(self.uri, offsets, lengths,
                                            num_threads)
                if out is not None:
                    if self._crc:
                        # the native scatter path bypasses read() — run
                        # the same sidecar verification here
                        for key, off, payload in zip(indices, offsets,
                                                     out):
                            want = self._crc.get(off)
                            if want is not None and \
                                    zlib.crc32(payload) & 0xFFFFFFFF \
                                    != want:
                                raise RecordCorrupt(
                                    self.uri, key,
                                    "crc mismatch on batched read")
                    return out
        return [self.read_idx(i) for i in indices]


# image record header (src/io/image_recordio.h:110 / recordio.py:344)
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a label header + byte payload into one record (recordio.py:355)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record into header + payload (recordio.py:388)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack to (header, image ndarray) — decodes jpg/png payloads
    (recordio.py:415). Uses PIL if available, else raw numpy pass-through
    for .npy-packed payloads."""
    header, s = unpack(s)
    img = _imdecode(s, iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (recordio.py:451)."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def _imencode(img, quality, img_fmt):
    try:
        from PIL import Image
        import io as _io
        buf = _io.BytesIO()
        Image.fromarray(np.asarray(img).astype(np.uint8)).save(
            buf, format="JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG",
            quality=quality)
        return buf.getvalue()
    except ImportError:
        # fallback: raw .npy serialization (not interchange-compatible)
        import io as _io
        buf = _io.BytesIO()
        np.save(buf, np.asarray(img))
        return buf.getvalue()


def _imdecode(s, iscolor=-1):
    if s[:6] == b"\x93NUMPY":
        import io as _io
        return np.load(_io.BytesIO(s))
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(s)))
        return img
    except ImportError:
        raise RuntimeError("No image decoder available (PIL missing and "
                           "payload is not npy)")
