"""Exporters over the telemetry ring: chrome://tracing JSON, an
MXNet-style aggregate-stats percentile table, and a Prometheus textfile.

Reference analogues: profiler.h DumpProfile() emits chrome tracing;
AggregateStats::DumpTable() the text table. The Prometheus writer is the
long-run addition (TF's system paper argues production operation needs
scrapeable metrics, not just post-hoc traces): point a node_exporter
textfile collector at MXNET_OBS_PROM and scrape counters per step.
"""

import json

from . import core
from .. import _fastenv

__all__ = ["chrome_trace", "dump_chrome_trace", "aggregate",
           "aggregate_table", "prometheus_text", "write_prometheus"]


# ------------------------------------------------------ chrome trace --

def chrome_trace(extra_events=None):
    """The ring as a chrome://tracing (catapult) JSON object. Spans are
    "X" complete events, counter samples "C" events; load the file at
    chrome://tracing or ui.perfetto.dev. Every event carries this
    process's rank as its ``pid`` so rank-local traces merge into
    per-rank lanes (``dist.merge_traces``); ``otherData`` carries the
    rank + barrier clock anchor the merge aligns timelines with."""
    from . import dist
    from . import histogram as _hist
    rank = dist.process_index()
    events = [{"name": "process_name", "ph": "M", "pid": rank,
               "args": {"name": "rank %d" % rank}}]
    last_ts = 0
    for rec in core.records():
        ph, name, cat, ts, val, tid, args = rec
        last_ts = max(last_ts, ts)
        if ph == "X":
            events.append({"name": name, "cat": cat, "ph": "X",
                           "ts": ts, "dur": val, "pid": rank, "tid": tid,
                           "args": args})
        elif ph == "C":
            events.append({"name": name, "cat": cat, "ph": "C",
                           "ts": ts, "pid": rank,
                           "args": {name.rsplit(".", 1)[-1]: val}})
        elif ph == "F":
            # flow events: val is (phase, flow_id); "s"/"t"/"f" chains
            # sharing an id render as one arrowed flow in the viewer
            fph, fid = val
            ev = {"name": name, "cat": cat, "ph": fph, "ts": ts,
                  "pid": rank, "tid": tid, "id": fid, "args": args}
            if fph == "f":
                ev["bp"] = "e"     # bind the finish to its slice
            events.append(ev)
        else:
            events.append({"name": name, "cat": cat, "ph": "i",
                           "ts": ts, "pid": rank, "tid": tid, "s": "t",
                           "args": args})
    # histogram snapshots: a counter row per histogram (quantiles
    # visible in the viewer) at the trace's end; the full mergeable
    # bucket state rides otherData.histograms
    hist_states = _hist.states()
    for name, h in sorted(_hist.histograms().items()):
        if h.count:
            events.append({"name": name, "cat": "histogram", "ph": "C",
                           "ts": last_ts, "pid": rank,
                           "args": h.quantiles()})
    if extra_events:
        events.extend(extra_events)
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"recorder": "mxnet_tpu.observability",
                           "rank": rank,
                           "num_processes": dist.process_count(),
                           "clock_anchor": dist.clock_anchor(),
                           "histograms": hist_states,
                           "dropped_records": core.dropped()}}
    return trace


def dump_chrome_trace(filename, extra_events=None):
    with open(filename, "w") as f:
        json.dump(chrome_trace(extra_events), f)
    return filename


# -------------------------------------------------- aggregate stats --

def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def aggregate():
    """Reduce the ring + counter registry to per-name stats.

    Returns {"spans": {name: stats}, "counters": {name: stats}} where
    span stats are over durations (ms) and counter stats over the added
    deltas (gauges: observed values); p50/p99 come from the ring samples
    (a suffix when the ring wrapped — count/total stay exact for
    counters because the registry accumulates independently).
    """
    span_samples = {}
    counter_samples = {}
    for rec in core.records():
        ph, name, _cat, _ts, val, _tid, args = rec
        if ph == "X":
            span_samples.setdefault(name, []).append(val / 1000.0)
        elif ph == "C":
            counter_samples.setdefault(name, []).append(
                args.get("delta", val))
    spans = {}
    for name, vals in sorted(span_samples.items()):
        vals.sort()
        spans[name] = {
            "count": len(vals), "total_ms": sum(vals),
            "min_ms": vals[0], "max_ms": vals[-1],
            "p50_ms": _percentile(vals, 0.50),
            "p99_ms": _percentile(vals, 0.99)}
    counters = {}
    for name, c in sorted(core.counters().items()):
        vals = sorted(counter_samples.get(name, []))
        counters[name] = {
            "count": c.count, "total": c.total,
            "min": c.min if c.min is not None else 0.0,
            "max": c.max if c.max is not None else 0.0,
            "p50": _percentile(vals, 0.50),
            "p99": _percentile(vals, 0.99),
            "value": c.value}
    from . import histogram as _hist
    hists = {name: h.snapshot()
             for name, h in sorted(_hist.histograms().items())}
    return {"spans": spans, "counters": counters, "histograms": hists}


def _format_timeseries():
    """The "Time-series (last window)" aggregate-table section: one
    line per sampled ring — points held, first/last values, and the
    window-mean rate for counters (timeseries.py)."""
    from . import timeseries as _ts
    if not _ts.ticks():
        return []
    win = _ts.last_window()
    lines = ["", "Time-series (last window: %d points max, %d ms "
             "interval, %d ticks)" % (win["window"], win["interval_ms"],
                                      win["ticks"])]
    fmt = "  %-40s %6s %12s %12s %12s"
    lines.append(fmt % ("Name", "Points", "First", "Last", "Rate/s"))
    for name, ent in sorted(win["series"].items()):
        vals = ent["values"]
        if not vals:
            continue
        rs = ent.get("rate_per_s") or []
        rate = ("%.3g" % (sum(rs) / len(rs))) if rs else "-"
        lines.append(fmt % (name, len(vals), "%g" % vals[0],
                            "%g" % vals[-1], rate))
    return lines


def aggregate_table():
    """The stats as a text table (reference AggregateStats::DumpTable):
    one section for span phases (ms), one for counters (raw values)."""
    agg = aggregate()
    lines = ["Profile Statistics (mxnet_tpu.observability)",
             "  Note: span times in ms; counter rows aggregate the "
             "added deltas, Value is the running total."]
    fmt = "%-36s %8s %12s %10s %10s %10s %10s"
    lines.append("")
    lines.append("Spans (phases)")
    lines.append("=" * 14)
    lines.append(fmt % ("Name", "Count", "Total(ms)", "Min", "Max",
                        "P50", "P99"))
    for name, s in agg["spans"].items():
        lines.append(fmt % (name, s["count"], "%.3f" % s["total_ms"],
                            "%.3f" % s["min_ms"], "%.3f" % s["max_ms"],
                            "%.3f" % s["p50_ms"], "%.3f" % s["p99_ms"]))
    fmtc = "%-36s %8s %12s %10s %10s %10s %10s %12s"
    lines.append("")
    lines.append("Counters")
    lines.append("=" * 8)
    lines.append(fmtc % ("Name", "Count", "Total", "Min", "Max",
                         "P50", "P99", "Value"))
    for name, s in agg["counters"].items():
        lines.append(fmtc % (name, s["count"], "%g" % s["total"],
                             "%g" % s["min"], "%g" % s["max"],
                             "%g" % s["p50"], "%g" % s["p99"],
                             "%g" % s["value"]))
    if agg["histograms"]:
        fmth = "%-32s %8s %12s %10s %10s %10s %10s %10s %10s"
        lines.append("")
        lines.append("Histograms (log-bucketed, exact count/sum)")
        lines.append("=" * 10)
        lines.append(fmth % ("Name", "Count", "Sum", "Mean", "P50",
                             "P90", "P99", "P99.9", "Max"))
        for name, h in agg["histograms"].items():
            lines.append(fmth % (
                name, h["count"], "%.3f" % h["sum"], "%.3f" % h["mean"],
                "%.3f" % h["p50"], "%.3f" % h["p90"],
                "%.3f" % h["p99"], "%.3f" % h["p999"],
                "%.3f" % h["max"]))
    from . import events as _events
    lines.extend(_events.format_recent())
    lines.extend(_format_timeseries())
    from . import dist
    lines.extend(dist.format_skew_table())
    from . import attribution
    lines.extend(attribution.format_ops_table())
    from . import costmodel
    lines.extend(costmodel.format_calibration_table())
    from . import goodput
    lines.extend(goodput.format_table_section())
    if core.dropped():
        lines.append("")
        lines.append("(%d oldest records dropped from the ring; "
                     "percentiles cover the retained suffix)"
                     % core.dropped())
    return "\n".join(lines)


# ------------------------------------------------- prometheus --------

def _prom_name(name):
    """One name sanitized to the Prometheus charset [a-zA-Z0-9_]
    (leading digits get a ``_`` prefix). Lossy on its own — named
    scopes like ``block[0]/attn`` and ``block(0).attn`` collapse to
    the same series — so exposition paths use :func:`_prom_name_map`
    for a collision-free mapping over the whole name set."""
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s or "_"


def _prom_name_map(names):
    """{original -> sanitized-and-unique} over ``names``. Collisions
    (distinct originals sanitizing to the same series name) get a
    deterministic ``_2``/``_3``... suffix in sorted-original order —
    the sorted-first original keeps the bare name, so the mapping is
    stable for a given name set regardless of iteration order."""
    by_sanitized = {}
    for name in sorted(set(names)):
        by_sanitized.setdefault(_prom_name(name), []).append(name)
    out = {}
    used = set(by_sanitized)
    for base in sorted(by_sanitized):
        members = by_sanitized[base]
        out[members[0]] = base
        n = 2
        for name in members[1:]:
            cand = "%s_%d" % (base, n)
            while cand in used:
                n += 1
                cand = "%s_%d" % (base, n)
            used.add(cand)
            out[name] = cand
            n += 1
    return out


def prometheus_text():
    """Prometheus exposition format: spans as summary-style series
    (count/sum + p50/p99 quantile samples), counters as *_total plus a
    last-value gauge. Suitable for a node_exporter textfile collector
    on long runs."""
    agg = aggregate()
    lines = [
        "# HELP mxnet_obs_span_ms host-side phase spans "
        "(mxnet_tpu.observability)",
        "# TYPE mxnet_obs_span_ms summary"]
    for name, s in agg["spans"].items():
        lab = 'phase="%s"' % name
        lines.append('mxnet_obs_span_ms_count{%s} %d' % (lab, s["count"]))
        lines.append('mxnet_obs_span_ms_sum{%s} %.6f'
                     % (lab, s["total_ms"]))
        for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
            lines.append('mxnet_obs_span_ms{%s,quantile="%s"} %.6f'
                         % (lab, q, s[key]))
    cmap = _prom_name_map(agg["counters"])
    lines.append("# HELP mxnet_obs_counter_total accumulated counter "
                 "deltas")
    lines.append("# TYPE mxnet_obs_counter_total counter")
    for name, s in agg["counters"].items():
        lines.append('mxnet_obs_counter_total{name="%s"} %g'
                     % (cmap[name], s["total"]))
    lines.append("# HELP mxnet_obs_value last recorded value per "
                 "counter/gauge")
    lines.append("# TYPE mxnet_obs_value gauge")
    for name, s in agg["counters"].items():
        lines.append('mxnet_obs_value{name="%s"} %g'
                     % (cmap[name], s["value"]))
    from . import histogram as _hist
    hists = _hist.histograms()
    if hists:
        lines.append("# HELP mxnet_obs_hist log-bucketed latency "
                     "histograms (serving.* request distributions)")
        lines.append("# TYPE mxnet_obs_hist histogram")
        hmap = _prom_name_map(hists)
        for name, h in sorted(hists.items()):
            pname = hmap[name]
            for le, cum in h.cumulative_buckets():
                lines.append(
                    'mxnet_obs_hist_bucket{name="%s",le="%s"} %d'
                    % (pname,
                       "+Inf" if le == float("inf") else "%g" % le,
                       cum))
            lines.append('mxnet_obs_hist_sum{name="%s"} %.6f'
                         % (pname, h.sum))
            lines.append('mxnet_obs_hist_count{name="%s"} %d'
                         % (pname, h.count))
            for q, label in _hist.QUANTILES:
                lines.append(
                    'mxnet_obs_hist_quantile{name="%s",quantile="%s"} '
                    '%.6f' % (pname, q, h.percentile(q)))
    anomalies = [(name, s) for name, s in agg["counters"].items()
                 if name.startswith("obs.anomaly.")]
    if anomalies:
        lines.append("# HELP mxnet_obs_anomaly trend-detector firings "
                     "(timeseries.py detectors over fleet history)")
        lines.append("# TYPE mxnet_obs_anomaly counter")
        amap = _prom_name_map(n[len("obs.anomaly."):]
                              for n, _s in anomalies)
        for name, s in anomalies:
            lines.append('mxnet_obs_anomaly_%s %g'
                         % (amap[name[len("obs.anomaly."):]],
                            s["value"]))
    from . import goodput
    lines.extend(goodput.prometheus_lines())
    from . import dist
    lines.append("# HELP mxnet_obs_rank this process's rank (label the "
                 "scrape per worker in multi-host jobs)")
    lines.append("# TYPE mxnet_obs_rank gauge")
    lines.append('mxnet_obs_rank %d' % dist.process_index())
    lines.append('mxnet_obs_dropped_records %d' % core.dropped())
    return "\n".join(lines) + "\n"


def write_prometheus(path=None):
    """Write the textfile; ``path`` defaults to MXNET_OBS_PROM. The
    write goes through a .tmp rename so a concurrent scrape never sees
    a torn file. Returns the path, or None when no target configured.
    Multi-process runs rank-suffix the file (rank 0 keeps the bare
    name) — one textfile per worker, no clobbering."""
    import os
    path = path or _fastenv.get("MXNET_OBS_PROM")
    if not path:
        return None
    from . import dist
    path = dist.rank_trace_path(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(prometheus_text())
    os.replace(tmp, path)
    return path
