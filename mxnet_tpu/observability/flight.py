"""Black-box flight recorder: one incident bundle per abnormal path.

PRs 11-15 gave every failure class a tested *recovery*; this module
gives them a *forensic artifact*. On any abnormal path — the exit
taxonomy (43 watchdog / 44 shrink / 45 boundary / 46 quarantine /
47 OOM / 143 SIGTERM), a watchdog post-mortem, a structural OOM, a
breaker opening, a rollout rollback, a fired chaos fault, or an
unhandled exception (``install()`` chains ``sys.excepthook``) —
``record_incident(cause, ...)`` atomically dumps a rank-suffixed,
CRC-framed incident bundle into the ``flight`` sideband
(``MXNET_OBS_FLIGHT_DIR`` / ``MXNET_OBS_SIDEBAND_DIR``, defaulting to
a per-uid temp directory so the recorder works before anyone
configures it):

    MXFLIGHT1 <crc32> <len>\\n{ json payload }

The payload carries everything the post-incident questions need:
cause + taxonomy class, the last time-series window
(``timeseries.last_window()``), recent spans and decision events, the
counter registry, every ``MXNET_*`` env knob, the registered
``health_snapshot()`` providers (serving/router register themselves —
journal positions ride in their snapshots), the membudget snapshot,
and the checkpoint lineage head. ``tools/obs_incident.py`` merges
bundles from many ranks/replicas on the PR 3 clock anchor.

Guards: bundles are only written when telemetry is on (the PR 2
off-path contract — with ``MXNET_OBS`` unset every hook is one guarded
branch) and ``MXNET_OBS_FLIGHT`` is not ``0``; each distinct cause is
capped at ``MXNET_OBS_FLIGHT_PER_CAUSE`` bundles per process (default
4 — retry loops must not flood the sideband) and the directory is
pruned to ``MXNET_OBS_FLIGHT_KEEP`` newest bundles (default 64).
``record_incident`` never raises: the flight recorder must never turn
an incident into a second incident.
"""

import atexit
import json
import os
import sys
import threading
import time
import traceback
import weakref
import zlib

from . import core
from . import events as _events
from . import sideband
from . import timeseries as _ts
from .. import _fastenv

__all__ = ["MAGIC", "EXIT_TAXONOMY", "BundleError", "enabled",
           "record_incident", "note_exit", "read_bundle",
           "list_bundles", "last_incident", "incidents_written",
           "register_context", "install", "reset"]

MAGIC = b"MXFLIGHT1"
SCHEMA = 1

# supervisor-visible exit codes -> failure class (docs/ROBUSTNESS.md)
EXIT_TAXONOMY = {
    0: "done",
    43: "watchdog_abort",
    44: "elastic_shrink",
    45: "elastic_boundary",
    46: "quarantine",
    47: "oom_structural",
    130: "sigint",
    143: "sigterm",
}

DEFAULT_PER_CAUSE = 4
DEFAULT_KEEP = 64
SPAN_TAIL = 128          # core-ring records per bundle
EVENT_TAIL = 64          # decision events per bundle

_lock = threading.Lock()
_seq = 0
_per_cause = {}
_last_incident = None
_providers = {}          # name -> weak or strong zero-arg callable
_installed = False
_prev_excepthook = None


class BundleError(Exception):
    """A bundle failed to parse; ``evidence`` names what broke
    (``torn-header`` / ``bad-magic`` / ``torn-payload`` /
    ``crc-mismatch`` / ``bad-json``)."""

    def __init__(self, evidence, detail=""):
        self.evidence = evidence
        super(BundleError, self).__init__(
            "%s%s" % (evidence, (": " + detail) if detail else ""))


def enabled():
    """Record bundles? Telemetry must be on AND the recorder not
    explicitly killed — this is the one guarded branch on every
    failure-path hook."""
    if not core.enabled():
        return False
    v = _fastenv.get("MXNET_OBS_FLIGHT")
    return v is None or v not in ("", "0", "false", "False")


def _per_cause_cap():
    return int(_fastenv.get("MXNET_OBS_FLIGHT_PER_CAUSE",
                            DEFAULT_PER_CAUSE))


def _keep():
    return int(_fastenv.get("MXNET_OBS_FLIGHT_KEEP", DEFAULT_KEEP))


def _slug(cause):
    out = []
    for ch in str(cause).lower():
        out.append(ch if ch.isalnum() else "-")
    s = "".join(out).strip("-")
    while "--" in s:
        s = s.replace("--", "-")
    return s or "unknown"


def classify(cause, exit_code=None):
    """Map an incident to its taxonomy class: an explicit exit code
    wins; otherwise the cause's leading token."""
    if exit_code is not None and exit_code in EXIT_TAXONOMY:
        return EXIT_TAXONOMY[exit_code]
    head = str(cause).split(".", 1)[0]
    return {"chaos": "chaos_fault", "exception": "unhandled_exception",
            "watchdog": "watchdog_abort", "oom": "oom_structural",
            "breaker": "breaker_open", "rollout": "rollout_rollback",
            "elastic": "elastic_generation",
            "sigterm": "sigterm"}.get(head, head)


def register_context(name, fn):
    """Register a zero-arg snapshot provider (e.g. a batcher's
    ``health_snapshot``) folded into every bundle's ``health`` map.
    Bound methods are held weakly so registration never pins a
    serving stack in memory; a dead provider silently drops out."""
    try:
        ref = weakref.WeakMethod(fn)
    except TypeError:
        ref = lambda fn=fn: fn
    with _lock:
        _providers[str(name)] = ref


def _provider_snapshots():
    with _lock:
        items = list(_providers.items())
    out = {}
    dead = []
    for name, ref in items:
        fn = ref()
        if fn is None:
            dead.append(name)
            continue
        try:
            out[name] = fn()
        except Exception as exc:       # noqa: BLE001 — best effort
            out[name] = {"error": "%s: %s" % (type(exc).__name__, exc)}
    if dead:
        with _lock:
            for name in dead:
                _providers.pop(name, None)
    return out


def _lineage_head():
    try:
        from ..models import checkpoint as _ckpt
        return _ckpt.lineage_head()
    except Exception:                  # noqa: BLE001 — best effort
        return None


def _rank():
    # the barrier clock anchor's rank wins when present — it is pinned
    # at calibration time, while jax.process_index() needs a live
    # distributed runtime (absent in post-mortem/atexit contexts)
    try:
        from . import dist as _dist
        anchor = _dist.clock_anchor()
        if anchor and "rank" in anchor:
            return int(anchor["rank"])
        return _dist.process_index()
    except Exception:                  # noqa: BLE001
        return 0


def _anchor():
    try:
        from . import dist as _dist
        return _dist.clock_anchor()
    except Exception:                  # noqa: BLE001
        return None


def _span_tail():
    out = []
    for rec in core.records()[-SPAN_TAIL:]:
        ph, name, cat, ts, val, _tid, args = rec
        if ph == "F":
            val = list(val)
        try:
            json.dumps(args)
        except (TypeError, ValueError):
            args = {k: str(v) for k, v in args.items()}
        out.append([ph, name, cat, ts, val, args])
    return out


def _payload(cause, exit_code, extra):
    counters = {}
    for name, c in core.counters().items():
        counters[name] = {"value": c.value, "count": c.count,
                          "total": c.total}
    env = {k: v for k, v in os.environ.items()
           if k.startswith("MXNET_")}
    health = _provider_snapshots()
    try:
        from . import membudget as _mb
        health["membudget"] = _mb.healthz_snapshot()
    except Exception:                  # noqa: BLE001
        pass
    try:
        from . import goodput as _goodput
        health["goodput"] = _goodput.healthz_snapshot()
    except Exception:                  # noqa: BLE001
        pass
    doc = {
        "schema": SCHEMA,
        "cause": str(cause),
        "taxonomy": classify(cause, exit_code),
        "exit_code": exit_code,
        "rank": _rank(),
        "pid": os.getpid(),
        "wall_time_s": time.time(),
        "mono_us": core._now_us(),
        "clock_anchor": _anchor(),
        "env": env,
        "counters": counters,
        "events": [[t, k, f] for t, k, f in _events.recent(EVENT_TAIL)],
        "spans": _span_tail(),
        "timeseries": _ts.last_window(),
        "health": health,
        "lineage_head": _lineage_head(),
        "dropped_records": core.dropped(),
    }
    if extra:
        safe = {}
        for k, v in extra.items():
            try:
                json.dumps(v)
                safe[k] = v
            except (TypeError, ValueError):
                safe[k] = str(v)
        doc["context"] = safe
    return doc


def frame(doc):
    """CRC-frame a payload dict -> bytes (the on-disk bundle form)."""
    body = json.dumps(doc, sort_keys=True,
                      default=str).encode("utf-8")
    head = b"%s %08x %d\n" % (MAGIC, zlib.crc32(body) & 0xFFFFFFFF,
                              len(body))
    return head + body


def record_incident(cause, exit_code=None, dirpath=None, **extra):
    """Dump one incident bundle. Returns the bundle path, or None when
    the recorder is off, capped for this cause, or anything at all
    goes wrong — never raises."""
    global _seq, _last_incident
    try:
        if not enabled():
            return None
        slug = _slug(cause)
        with _lock:
            n = _per_cause.get(slug, 0)
            if n >= _per_cause_cap():
                return None
            _per_cause[slug] = n + 1
            _seq += 1
            seq = _seq
        d = dirpath or sideband.resolve("flight", create=True)
        if not d:
            return None
        doc = _payload(cause, exit_code, extra)
        name = ("incident.%s.rank%d.pid%d.%03d.json"
                % (slug, doc["rank"], os.getpid(), seq))
        path = os.path.join(d, name)
        sideband.write_atomic(path, frame(doc))
        sideband.prune(d, prefix="incident.", keep=_keep())
        with _lock:
            _last_incident = path
        core.counter("obs.incidents").add(1)
        return path
    except Exception:                  # noqa: BLE001 — never raise
        return None


def note_exit(code, cause=None, **extra):
    """The exit-taxonomy hook: record a bundle for a supervisor-visible
    abnormal exit code (no-op for 0). Returns the bundle path."""
    code = int(code)
    if code == 0:
        return None
    if cause is None:
        cause = "exit." + EXIT_TAXONOMY.get(code, "crash")
    return record_incident(cause, exit_code=code, **extra)


def read_bundle(path):
    """Parse + verify one bundle. Raises BundleError with named
    evidence on torn or corrupt files."""
    with open(path, "rb") as f:
        data = f.read()
    nl = data.find(b"\n")
    if nl < 0:
        raise BundleError("torn-header", "no newline in %d bytes"
                          % len(data))
    parts = data[:nl].split()
    if len(parts) != 3 or parts[0] != MAGIC:
        raise BundleError("bad-magic", repr(data[:nl][:64]))
    try:
        want_crc = int(parts[1], 16)
        want_len = int(parts[2])
    except ValueError:
        raise BundleError("bad-magic", repr(data[:nl][:64]))
    body = data[nl + 1:]
    if len(body) != want_len:
        raise BundleError("torn-payload", "expected %d bytes, found %d"
                          % (want_len, len(body)))
    got_crc = zlib.crc32(body) & 0xFFFFFFFF
    if got_crc != want_crc:
        raise BundleError("crc-mismatch", "expected %08x, computed %08x"
                          % (want_crc, got_crc))
    try:
        return json.loads(body.decode("utf-8"))
    except ValueError as exc:
        raise BundleError("bad-json", str(exc))


def list_bundles(dirpath=None):
    """Bundle paths under the flight sideband (or ``dirpath``), oldest
    first by (mtime, name)."""
    d = dirpath or sideband.resolve("flight")
    if not d or not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        if name.startswith("incident.") and name.endswith(".json"):
            p = os.path.join(d, name)
            try:
                out.append((os.path.getmtime(p), name, p))
            except OSError:
                continue
    return [p for _m, _n, p in sorted(out)]


def last_incident():
    """Path of the newest bundle this process wrote (``/healthz``)."""
    with _lock:
        return _last_incident


def incidents_written():
    with _lock:
        return sum(_per_cause.values())


def _excepthook(etype, value, tb):
    try:
        frames = traceback.format_exception(etype, value, tb)
        record_incident(
            "exception.%s" % etype.__name__, error=str(value),
            traceback=[ln.rstrip() for ln in frames][-20:])
    except Exception:                  # noqa: BLE001 — never mask
        pass
    if _prev_excepthook is not None:
        _prev_excepthook(etype, value, tb)


def _atexit():
    # debug knob: force a shutdown bundle even on clean exits (the
    # excepthook already covers crashes; explicit hooks cover the
    # exit taxonomy, whose os._exit paths skip atexit anyway)
    v = _fastenv.get("MXNET_OBS_FLIGHT_ATEXIT")
    if v and v not in ("0", "false", "False"):
        record_incident("atexit.shutdown")


def install():
    """Chain the unhandled-exception hook (and the atexit debug hook)
    once per process. Called from the observability package import
    when telemetry is on; a no-op (one guarded branch) otherwise."""
    global _installed, _prev_excepthook
    if _installed or not enabled():
        return False
    _installed = True
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    atexit.register(_atexit)
    return True


def reset():
    """Forget per-process incident state (tests). Does not uninstall
    the excepthook."""
    global _seq, _per_cause, _last_incident
    with _lock:
        _seq = 0
        _per_cause = {}
        _last_incident = None
        _providers.clear()
