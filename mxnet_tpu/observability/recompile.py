"""Recompile detector — catch silent XLA retraces with the signature
that caused them.

On this stack a "recompile" is jax re-tracing a jitted computation
because an argument signature changed (new shapes/dtypes, a flipped
static flag, a weakly-typed scalar). The reference framework never had
this failure mode — its graphs were explicit — but here a
shape-polymorphic input silently multiplies step latency by the
compile time, and nothing in the training loop says so.

Mechanism: jax.monitoring publishes per-compile duration events
(``/jax/core/compile/jaxpr_trace_duration`` on every trace,
``backend_compile_duration`` on every executable build). One listener,
registered lazily, forwards them to the active detector. The events
carry no function identity, so instrumented call sites (CachedOp,
Executor) drop a breadcrumb first — ``note_call(origin, signature)``
into a thread-local — and the detector attributes a compile event to
the innermost breadcrumb live on that thread when it fires. Python-
level variant builds (a new CachedOp fn cache entry) report through
``record_retrace`` with an exact signature.

Steady-state budget: first-time compiles are legitimate, so misses only
count against the budget after ``mark_steady()`` — Trainer.step /
Module.update arm it automatically once a step past
``MXNET_OBS_WARMUP_STEPS`` (default 1) completes with NO compiles, i.e.
stability is observed, not assumed. Past
``MXNET_OBS_RECOMPILE_BUDGET`` steady misses (default 2) the detector
warns once with the attributed signatures.
"""

import collections
import threading
import warnings

from . import core
from .. import _fastenv

__all__ = ["JAXPR_TRACE_EVENT", "BACKEND_COMPILE_EVENT",
           "RecompileDetector", "get_detector", "note_call",
           "record_retrace", "step_boundary"]

JAXPR_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_tls = threading.local()
_detector = None
_listener_installed = False
_lock = threading.Lock()


def default_budget():
    return int(_fastenv.get("MXNET_OBS_RECOMPILE_BUDGET", 2))


def warmup_steps():
    return int(_fastenv.get("MXNET_OBS_WARMUP_STEPS", 1))


class RecompileDetector(object):
    """Per-process retrace ledger. ``events`` holds the most recent
    4096 compile records: dicts with kind ('trace'|'backend_compile'|
    'variant'), origin, signature, duration_s and steady flag."""

    def __init__(self, budget=None):
        self.budget = default_budget() if budget is None else int(budget)
        self.events = collections.deque(maxlen=4096)
        self.steady = False
        self.misses = 0          # trace events seen while recording
        self.steady_misses = 0   # trace events after mark_steady()
        self.flagged = False
        self._steps = 0
        self._step_start_misses = 0
        self._lock = threading.Lock()

    # ----------------------------------------------------- lifecycle --
    def reset(self, budget=None):
        with self._lock:
            if budget is not None:
                self.budget = int(budget)
            self.events.clear()
            self.steady = False
            self.misses = 0
            self.steady_misses = 0
            self.flagged = False
            self._steps = 0
            self._step_start_misses = 0

    def mark_steady(self):
        """Arm the budget: every later trace is a silent retrace."""
        self.steady = True

    def step_boundary(self):
        """One train step completed. Arm once a post-warmup step runs
        with NO compiles at all — "the graphs stabilized" observed
        rather than assumed (a fixed step count would misfire on
        programs that legitimately compile new jits for a few steps:
        metrics, logging ops, the optimizer's first update)."""
        with self._lock:
            self._steps += 1
            if not self.steady and self._steps > warmup_steps() \
                    and self.misses == self._step_start_misses:
                self.steady = True
            self._step_start_misses = self.misses

    # ------------------------------------------------------- ingest --
    def _push(self, kind, origin, signature, duration):
        rec = {"kind": kind, "origin": origin, "signature": signature,
               "duration_s": duration, "steady": self.steady}
        over = False
        with self._lock:
            self.events.append(rec)
            if kind == "trace":
                self.misses += 1
                if self.steady:
                    self.steady_misses += 1
                    if self.steady_misses >= self.budget \
                            and not self.flagged:
                        self.flagged = True
                        over = True
        core.record_instant(
            "recompile." + kind, cat="recompile",
            args={"origin": origin, "signature": signature,
                  "steady": rec["steady"],
                  # the goodput ledger reconstructs the compile
                  # interval [ts - duration, ts] from this instant
                  "duration_s": duration})
        core.counter("recompile." + kind).add(1)
        if kind == "backend_compile":
            # a fresh executable exists — per-operator attribution must
            # re-analyze the origin's program (attribution.py caches
            # the HLO breakdown per executable)
            from . import attribution
            attribution.on_compile(origin, kind)
        if over:
            self._warn()

    def _warn(self):
        recent = [e for e in list(self.events)[-16:]
                  if e["steady"] and e["kind"] == "trace"]
        culprits = "; ".join(
            "%s%s" % (e["origin"] or "<jit>",
                      " " + e["signature"] if e["signature"] else "")
            for e in recent[-4:]) or "<unattributed jit>"
        warnings.warn(
            "mxnet_tpu.observability: %d XLA retraces after steady "
            "state (budget %d) — a jit is being re-traced per call, "
            "likely shape/dtype-polymorphic inputs. Recent: %s"
            % (self.steady_misses, self.budget, culprits),
            RuntimeWarning, stacklevel=3)

    def on_event(self, event, duration):
        if getattr(_tls, "suppress", 0):
            # report-time re-lowering (attribution._analyze) compiles on
            # purpose; counting it would flag the profiler as the leak
            return
        origin, signature = getattr(_tls, "call", (None, None))
        if event == JAXPR_TRACE_EVENT:
            self._push("trace", origin, signature, duration)
        elif event == BACKEND_COMPILE_EVENT:
            self._push("backend_compile", origin, signature, duration)


# -------------------------------------------------- module-level API --

def _listener(event, duration, **kwargs):
    det = _detector
    if det is None or not core.enabled():
        return
    if event is JAXPR_TRACE_EVENT or event is BACKEND_COMPILE_EVENT \
            or event in (JAXPR_TRACE_EVENT, BACKEND_COMPILE_EVENT):
        det.on_event(event, duration)


def get_detector():
    """The process detector; installs the jax.monitoring listener on
    first use (once per process — the listener itself gates on
    ``core.enabled()`` so an idle registration costs nothing except on
    compile events, which are rare by definition)."""
    global _detector, _listener_installed
    with _lock:
        if _detector is None:
            _detector = RecompileDetector()
        if not _listener_installed:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(
                _listener)
            _listener_installed = True
    return _detector


def note_call(origin, signature):
    """Breadcrumb: the jit boundary about to run on this thread. Any
    compile event firing before the next note is attributed to it.
    Call only when ``core.enabled()`` (signature formatting costs)."""
    get_detector()
    _tls.call = (origin, signature)


class suppress_events(object):
    """Context manager: compile/trace events fired on this thread are
    NOT counted by the detector (deliberate report-time lowering)."""

    def __enter__(self):
        _tls.suppress = getattr(_tls, "suppress", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.suppress -= 1


def record_retrace(origin, signature, duration=0.0):
    """Explicit retrace report for python-level variant builds (a new
    CachedOp fn-cache entry after the first, a new executor program)."""
    get_detector()._push("variant", origin, signature, duration)


def step_boundary():
    """Trainer hook: a full train step completed."""
    if _detector is not None or core.enabled():
        get_detector().step_boundary()


def signature_of(arrays, **flags):
    """Compact signature string for note_call: 'f32[2,3],f32[3] k=v'."""
    parts = []
    for a in arrays:
        dt = getattr(a, "dtype", None)
        sh = getattr(a, "shape", ())
        parts.append("%s[%s]" % (
            getattr(dt, "name", dt), ",".join(str(d) for d in sh)))
    sig = ",".join(parts)
    if flags:
        sig += " " + " ".join(
            "%s=%s" % (k, v) for k, v in sorted(flags.items()))
    return sig
