"""Telemetry core — ring-buffer span recorder + named counters/gauges.

Reference analogue: src/profiler/profiler.h:251 keeps a per-thread
profile record ring that DumpProfile() serializes to chrome://tracing
and AggregateStats reduces to a percentile table. Here the same role is
played by one process-wide ring of host-side records, because device-op
timing already belongs to XLA's profiler (jax.profiler / XPlane) — what
the runtime needs to observe for itself is the HOST orchestration:
step phases, collective dispatch, input pipeline, jit boundaries.

Design constraints (ISSUE 2 tentpole):

* near-zero cost when off — every instrumentation site guards on
  ``enabled()``, a module override check + one `_fastenv` dict read
  (~0.1 us); a disabled ``span`` allocates one slotted object and does
  nothing else. No locks, no time syscalls, no string formatting.
* thread-safe when on — the prefetch threads (io.py), the main step
  loop and jax.monitoring callbacks all record concurrently; one lock
  guards the ring head and the counter registry, and record payloads
  are built before taking it.
* bounded memory — a fixed-capacity ring (``MXNET_OBS_RING``, default
  65536 records) overwrites the oldest records; ``dropped`` reports how
  many fell off so exporters can say the trace is a suffix.

Knobs: ``MXNET_OBS=1`` enables recording; ``MXNET_OBS_RING`` sets ring
capacity (read when the ring is (re)built). ``set_enabled()`` overrides
the env for the profiler state machine (profiler.set_state/pause).
"""

import threading
import time

from .. import _fastenv

__all__ = ["enabled", "set_enabled", "span", "counter", "gauge",
           "histogram", "record_span", "record_instant", "record_flow",
           "records", "counters", "dropped", "reset", "ring_capacity",
           "Counter", "Gauge"]

DEFAULT_RING = 65536

# perf_counter epoch shared by every record so spans from different
# threads land on one consistent trace timeline
_EPOCH_NS = time.perf_counter_ns()

# None -> follow MXNET_OBS; True/False -> profiler state machine override
_override = None

_lock = threading.Lock()
_ring = [None] * 0
_head = 0
_total = 0
_counters = {}


def enabled():
    """Is recording on? Module override (profiler.set_state) beats the
    MXNET_OBS env knob. This is THE hot-path guard — keep it cheap."""
    if _override is not None:
        return _override
    v = _fastenv.get("MXNET_OBS")
    return v is not None and v not in ("", "0", "false", "False")


def set_enabled(value):
    """Override the env gate: True/False force, None reverts to env."""
    global _override
    _override = value


def ring_capacity():
    return int(_fastenv.get("MXNET_OBS_RING", DEFAULT_RING))


def _ensure_ring():
    global _ring
    if not _ring:
        _ring = [None] * max(ring_capacity(), 1)
    return _ring


def _now_us():
    return (time.perf_counter_ns() - _EPOCH_NS) // 1000


def _append(rec):
    global _head, _total
    with _lock:
        ring = _ensure_ring()
        ring[_head] = rec
        _head = (_head + 1) % len(ring)
        _total += 1


def record_span(name, cat, t0_ns, t1_ns, args=None):
    """Record one completed span. Timestamps are perf_counter_ns values
    (callers capture them outside the lock)."""
    _append(("X", name, cat, (t0_ns - _EPOCH_NS) // 1000,
             max((t1_ns - t0_ns) // 1000, 0),
             threading.get_ident(), args or {}))


def record_instant(name, cat="event", args=None):
    """Record a zero-duration marker."""
    _append(("i", name, cat, _now_us(), 0, threading.get_ident(),
             args or {}))


def record_flow(name, flow_id, phase, cat="flow", args=None):
    """Record one chrome-trace flow event: ``phase`` is ``"s"``
    (start), ``"t"`` (step) or ``"f"`` (finish). Events sharing
    ``(name, flow_id)`` render as one arrowed chain across lanes and
    threads — how a serving request's admit→decode→finish is tied
    together across pipeline-depth dispatches."""
    _append(("F", name, cat, _now_us(), (str(phase), int(flow_id)),
             threading.get_ident(), args or {}))


class span(object):
    """``with span("allreduce", cat="step", bytes=n):`` — records one
    "X" (complete) event when recording is on; a cheap no-op otherwise.
    Usable as a context manager or via explicit start()/stop()."""

    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name, cat="phase", **args):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = None

    def start(self):
        if enabled():
            self._t0 = time.perf_counter_ns()
        return self

    def stop(self):
        if self._t0 is not None:
            record_span(self.name, self.cat, self._t0,
                        time.perf_counter_ns(), self.args)
            self._t0 = None

    __enter__ = start

    def __exit__(self, *exc):
        self.stop()


class Counter(object):
    """Monotonic-by-convention named counter. ``add`` keeps running
    count/total/min/max of the deltas and drops a "C" sample in the
    ring so exporters can plot the series and compute percentiles."""

    __slots__ = ("name", "unit", "count", "total", "min", "max", "value")

    def __init__(self, name, unit=""):
        self.name = name
        self.unit = unit
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.value = 0.0

    def add(self, delta=1):
        delta = float(delta)
        with _lock:
            self.count += 1
            self.total += delta
            self.value += delta
            self.min = delta if self.min is None else min(self.min, delta)
            self.max = delta if self.max is None else max(self.max, delta)
        _append(("C", self.name, "counter", _now_us(), self.value,
                 threading.get_ident(), {"delta": delta}))

    def set(self, value):
        with _lock:
            delta = float(value) - self.value
            self.count += 1
            self.total += delta
            self.value = float(value)
            self.min = float(value) if self.min is None \
                else min(self.min, float(value))
            self.max = float(value) if self.max is None \
                else max(self.max, float(value))
        _append(("C", self.name, "counter", _now_us(), self.value,
                 threading.get_ident(), {}))


class Gauge(Counter):
    """A counter whose ``set`` is the primary verb (last value wins);
    min/max/count still aggregate the observed values."""

    __slots__ = ()

    def set(self, value):
        value = float(value)
        with _lock:
            self.count += 1
            self.total += value
            self.value = value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
        _append(("C", self.name, "gauge", _now_us(), value,
                 threading.get_ident(), {}))


def counter(name, unit=""):
    """Get-or-create the named counter (registry is process-global)."""
    c = _counters.get(name)
    if c is None:
        with _lock:
            c = _counters.get(name)
            if c is None:
                c = _counters[name] = Counter(name, unit)
    return c


def gauge(name, unit=""):
    g = _counters.get(name)
    if g is None:
        with _lock:
            g = _counters.get(name)
            if g is None:
                g = _counters[name] = Gauge(name, unit)
    return g


def histogram(name, unit=""):
    """Get-or-create the named log-bucketed histogram (bounded-memory
    distribution with mergeable buckets — ``histogram.Histogram``)."""
    from . import histogram as _h
    return _h.histogram(name, unit)


def records():
    """Snapshot of ring contents, oldest first."""
    with _lock:
        if not _ring:
            return []
        if _total <= len(_ring):
            out = [r for r in _ring[:_head] if r is not None]
        else:
            out = [r for r in _ring[_head:] + _ring[:_head]
                   if r is not None]
    return out


def counters():
    """Snapshot of the counter registry (name -> Counter)."""
    with _lock:
        return dict(_counters)


def dropped():
    """Records that fell off the ring (trace is a suffix when > 0)."""
    with _lock:
        return max(_total - len(_ring), 0) if _ring else 0


def reset():
    """Clear the ring, the counter registry and the histogram registry
    (tests, new profile sessions). The ring is rebuilt at the current
    MXNET_OBS_RING."""
    global _ring, _head, _total
    with _lock:
        _ring = [None] * 0
        _head = 0
        _total = 0
        _counters.clear()
    from . import histogram as _h
    _h.reset()
    from . import events as _ev
    _ev.reset()
    from . import timeseries as _ts
    _ts.reset()
