"""One resolver for every file sideband the runtime writes.

Three subsystems grew their own "drop a small file next to the run"
channel — the watchdog check-in/post-mortem directory
(``MXNET_OBS_WATCHDOG_DIR``), the elastic supervisor's generation /
shrink / quarantine records (``MXNET_ELASTIC_DIR``), and the flight
recorder's incident bundles (``MXNET_OBS_FLIGHT_DIR``, PR 17) — each
with its own env knob and its own ad-hoc cleanup. This module is the
single place that turns a *kind* into a directory and keeps any of
them from growing without bound:

* ``resolve(kind)`` — the kind-specific env knob wins; otherwise the
  shared root ``MXNET_OBS_SIDEBAND_DIR`` provides ``<root>/<kind>``;
  otherwise the kind's default (``None`` for watchdog/elastic — those
  sidebands are opt-in — and a per-user temp directory for ``flight``,
  because a flight recorder that needs configuring before a crash is
  not a flight recorder).
* ``write_atomic(path, data)`` — tmp + ``os.replace`` in the target
  directory, the same torn-write discipline as the prometheus textfile
  and the watchdog check-ins.
* ``prune(dirpath, ...)`` — bounded retention by count and/or age so
  long-lived supervisors don't leak sideband files; deterministic
  under an injected ``now`` for tests.

Resolution never creates directories unless asked (``create=True``)
and never raises on a missing root — sidebands are telemetry, and
telemetry must never break the run.
"""

import os
import tempfile

from .. import _fastenv

__all__ = ["KINDS", "resolve", "root", "write_atomic", "prune"]

# kind -> (dedicated env knob, default when neither knob nor root set)
KINDS = {
    "watchdog": ("MXNET_OBS_WATCHDOG_DIR", None),
    "elastic": ("MXNET_ELASTIC_DIR", None),
    "flight": ("MXNET_OBS_FLIGHT_DIR", "__tmp__"),
}

ROOT_ENV = "MXNET_OBS_SIDEBAND_DIR"


def root():
    """The shared sideband root (``MXNET_OBS_SIDEBAND_DIR``) or None."""
    return _fastenv.get(ROOT_ENV) or None


def _flight_default():
    # per-uid so a shared /tmp host doesn't cross-contaminate bundles
    try:
        uid = os.getuid()
    except AttributeError:             # pragma: no cover - non-posix
        uid = 0
    return os.path.join(tempfile.gettempdir(),
                        "mxnet_obs_incidents.%d" % uid)


def resolve(kind, create=False):
    """Directory for ``kind`` (one of ``KINDS``): the kind's own env
    knob beats the shared root beats the kind default. Returns None
    when the sideband is unconfigured and has no default."""
    try:
        env_key, default = KINDS[kind]
    except KeyError:
        raise ValueError("unknown sideband kind %r (have %s)"
                         % (kind, sorted(KINDS)))
    path = _fastenv.get(env_key) or None
    if path is None:
        shared = root()
        if shared:
            path = os.path.join(shared, kind)
        elif default == "__tmp__":
            path = _flight_default()
        else:
            path = default
    if path and create:
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:                # pragma: no cover - fs race/perm
            return None
    return path


def write_atomic(path, data):
    """Write ``data`` (bytes or str) to ``path`` via a same-directory
    tmp file + ``os.replace`` — a reader never sees a torn file, only
    the old content or the new. Returns ``path``."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def prune(dirpath, prefix="", keep=None, ttl_s=None, now=None):
    """Bounded retention for a sideband directory: of the files whose
    basename starts with ``prefix``, delete everything beyond the
    ``keep`` newest (by mtime) and everything older than ``ttl_s``
    seconds relative to ``now`` (default: the directory's newest
    mtime, so a wholly-idle sideband is never aged out by wall time
    alone). Missing directories and racing deletes are silently fine.
    Returns the list of removed paths (tests assert on it)."""
    if not dirpath or not os.path.isdir(dirpath):
        return []
    entries = []
    for name in os.listdir(dirpath):
        if prefix and not name.startswith(prefix):
            continue
        p = os.path.join(dirpath, name)
        try:
            if not os.path.isfile(p):
                continue
            entries.append((os.path.getmtime(p), p))
        except OSError:
            continue
    entries.sort(reverse=True)         # newest first
    removed = []
    victims = []
    if keep is not None and len(entries) > keep:
        victims.extend(entries[keep:])
        entries = entries[:keep]
    if ttl_s is not None and entries:
        ref = now if now is not None else entries[0][0]
        victims.extend((m, p) for m, p in entries if ref - m > ttl_s)
    for _mtime, p in victims:
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            continue
    return removed
