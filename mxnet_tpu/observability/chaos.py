"""Deterministic fault injection — the chaos layer the recovery paths
are proven against.

Large-scale jobs die in ways unit tests never exercise: a rank that
stops dispatching mid-collective, a preemption notice that lands in the
middle of ``save_checkpoint``, a gradient that goes NaN after three
days, a record read that hiccups once per epoch. The TensorFlow system
paper (PAPERS.md) makes user-level checkpointing plus automatic restart
the backbone of fault tolerance at scale; this module provides the
*inject* half of that loop so every detector and recovery path in the
repo (step guards, io retries, emergency checkpointing, the watchdog
escalation policy, serving requeue) is exercised by tests on the CPU
mesh instead of by waiting for real hardware to fail.

Faults are DETERMINISTIC: a rule fires on exact occurrence counts of a
named site, never on a random draw, so a failing chaos test replays
bit-for-bit. Sites are cheap named checkpoints on the hot paths —
``chaos.fire("kvstore.pushpull_fused", ...)`` — that reduce to one
guarded branch (``enabled()``: a list check + one `_fastenv` read, the
PR 2 cost model) when no spec is installed. With ``MXNET_CHAOS`` unset
and no programmatic rules there is no behavior change anywhere.

Spec grammar (``MXNET_CHAOS`` env var, or ``install(spec)``)::

    spec  := rule (';' rule)*
    rule  := <site-glob> ':' <fault> (':' key '=' value)*
    fault := delay | hang | error | nan | crash | sigterm | bitflip
             | oom

    keys: at=N     fire on the Nth match of this rule (0-based)
          every=N  fire on every Nth match (occ % N == 0)
          count=M  total firings allowed (default 1; 0 = unlimited)
          ms=F     delay/hang duration in milliseconds
                   (delay default 100, hang default 30000)
          rank=R   only on jax process R (other ranks don't count occs)
          code=C   exit code for crash (default 13)
          bit=B    bitflip: which bit of the element/byte to flip
          elem=I   bitflip: which element (array sites) or byte
                   (byte/file sites) to corrupt
          bytes=N  oom: the allocation size the injected
                   RESOURCE_EXHAUSTED claims (default 1 GiB)

    MXNET_CHAOS="kvstore.pushpull_fused:delay:ms=250:at=3"
    MXNET_CHAOS="io.read:error:count=2;trainer.grads:nan:at=5"

Programmatic rules stack on top of the env spec::

    from mxnet_tpu.observability import chaos
    chaos.inject("serving.dispatch", "error", at=2)
    ...
    chaos.reset()

Fault semantics at a site:

* ``delay`` — sleep ``ms`` (straggler injection; the PR 3 detector's
  natural prey).
* ``hang``  — block up to ``ms`` (default 30 s) or until ``release()``
  — a rank that stopped dispatching, the watchdog's prey.
* ``error`` — raise ``ChaosError`` (an ``OSError``, so io retry paths
  treat it as a transient read failure).
* ``nan``   — returned to the caller in the fired list; sites that own
  a value (gradients) poison it via ``poison_ndarrays``. Injecting a
  value corruption is necessarily cooperative — chaos cannot know the
  shape of every site's payload.
* ``crash`` — ``os._exit(code)``: SIGKILL semantics, no cleanup, no
  atexit — the commit-point torture test.
* ``sigterm`` — ``os.kill(getpid(), SIGTERM)``: a preemption notice;
  exercises the emergency-checkpoint handler.
* ``bitflip`` — silent data corruption: flip bit ``bit`` of element
  ``elem`` at the exact occurrence the rule selects, replayably.
  Cooperative like ``nan``: sites that own arrays use
  ``poison_bitflip``/``bitflip_array``, byte/file sites use
  ``corrupt_bytes``/``corrupt_file``. The integrity detectors
  (observability/integrity.py) are proven against this fault.
* ``oom``  — raise ``ChaosResourceExhausted``: a real-shaped XLA
  RESOURCE_EXHAUSTED (same message grammar the PJRT allocator emits,
  claiming ``bytes=N``), so every OOM recovery path — the membudget
  taxonomy, training accum re-lowering, serving's KV shrink-and-retry,
  the deferred checkpoint snapshot — replays deterministically on the
  CPU mesh. Sites: ``trainer.step``, ``serving.dispatch``,
  ``kv.pool.grow``, ``checkpoint.snapshot``.

Durable-serving sites (the PR 15 surface): ``journal.append`` fires
before every WAL record lands (``crash`` here is the kill-at-
commit-point torture; ``bitflip`` via ``corrupt_file`` rots a record
at rest), ``journal.replay`` fires per segment during recovery scan,
``serving.swap`` fires inside ``swap_weights`` after the lineage gate,
and ``router.rollout`` fires at each rolling-upgrade step (swap and
canary phases — ``error`` at the canary phase is the lying-canary
fault the auto-rollback is proven against).

``stats`` is the always-on cheap view (the ``kv.dispatch_stats``
pattern); with ``MXNET_OBS=1`` every firing also lands a
``chaos.inject`` instant + ``chaos.injected``/``chaos.<fault>``
counters in the trace, and skipped-update steps (the NaN guard) count
``chaos.skipped_steps`` — so a post-mortem trace shows exactly which
fault fired where.

The step guards (``MXNET_STEP_GUARD=1``) live here too: Trainer/Module
ask ``step_guard_enabled()`` + ``all_finite()`` before applying an
update, skip the step on non-finite loss/grads (backing off the AMP
loss scale when one is attached), and count the skip — weights are
never poisoned by one bad batch.
"""

import fnmatch
import os
import signal
import threading
import time

from . import core
from .. import _fastenv

__all__ = ["ChaosError", "ChaosResourceExhausted", "Rule", "enabled",
           "fire", "fire_rules",
           "inject", "install", "reset", "release", "rules", "stats",
           "poison_ndarrays", "poison_bitflip", "bitflip_array",
           "corrupt_bytes", "corrupt_file",
           "step_guard_enabled", "all_finite", "count_skipped_step"]

FAULTS = ("delay", "hang", "error", "nan", "crash", "sigterm",
          "bitflip", "oom")

DEFAULT_DELAY_MS = 100.0
DEFAULT_HANG_MS = 30000.0
DEFAULT_CRASH_CODE = 13
DEFAULT_OOM_BYTES = 1 << 30


class ChaosError(OSError):
    """The injected transient failure. Subclasses OSError so retrying
    readers (io.py) treat it exactly like a real flaky read."""


class ChaosResourceExhausted(RuntimeError):
    """The injected allocation failure. The message carries the
    RESOURCE_EXHAUSTED status text the PJRT allocator emits, so
    ``membudget.is_resource_exhausted`` — and any substring-matching
    handler written for the real XlaRuntimeError — routes it
    identically to a genuine device OOM."""


class Rule(object):
    """One parsed injection rule. ``seen`` counts matches (after the
    rank filter), ``fired`` counts executions — both are the replayable
    determinism this module is named for."""

    __slots__ = ("pattern", "fault", "at", "every", "count", "ms",
                 "rank", "code", "bit", "elem", "bytes", "seen",
                 "fired")

    def __init__(self, pattern, fault, at=None, every=None, count=1,
                 ms=None, rank=None, code=DEFAULT_CRASH_CODE,
                 bit=0, elem=0, bytes=DEFAULT_OOM_BYTES):
        if fault not in FAULTS:
            raise ValueError("unknown chaos fault %r (one of %s)"
                             % (fault, "/".join(FAULTS)))
        self.pattern = pattern
        self.fault = fault
        self.at = None if at is None else int(at)
        self.every = None if every is None else int(every)
        self.count = int(count)
        self.ms = None if ms is None else float(ms)
        self.rank = None if rank is None else int(rank)
        self.code = int(code)
        self.bit = int(bit)
        self.elem = int(elem)
        self.bytes = int(bytes)
        self.seen = 0
        self.fired = 0

    def __repr__(self):
        return ("Rule(%r, %r, at=%s, every=%s, count=%s, ms=%s, "
                "rank=%s, seen=%d, fired=%d)"
                % (self.pattern, self.fault, self.at, self.every,
                   self.count, self.ms, self.rank, self.seen,
                   self.fired))

    def matches(self, site):
        return fnmatch.fnmatchcase(site, self.pattern)

    def due(self):
        """Called under the lock with ``seen`` NOT yet incremented for
        this occurrence; decides whether this occurrence fires."""
        occ = self.seen
        if self.count and self.fired >= self.count:
            return False
        if self.at is not None:
            return occ == self.at
        if self.every is not None:
            return occ % self.every == 0
        return True


def parse_spec(spec):
    """``site:fault[:k=v]*`` rules joined by ``;`` -> list of Rule."""
    out = []
    for chunk in (spec or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) < 2:
            raise ValueError(
                "chaos rule %r needs at least <site>:<fault>" % chunk)
        kw = {}
        for kv in parts[2:]:
            if "=" not in kv:
                raise ValueError(
                    "chaos rule %r: expected key=value, got %r"
                    % (chunk, kv))
            k, v = kv.split("=", 1)
            if k not in ("at", "every", "count", "ms", "rank", "code",
                         "bit", "elem", "bytes"):
                raise ValueError(
                    "chaos rule %r: unknown key %r" % (chunk, k))
            kw[k] = v
        out.append(Rule(parts[0], parts[1], **kw))
    return out


_lock = threading.Lock()
_prog = []              # programmatic rules (inject()/install())
_env_spec = None        # spec string the cached _env_rules were built from
_env_rules = []
_release = threading.Event()

# always-on cheap counters (the kv.dispatch_stats pattern); obs
# counters mirror them when MXNET_OBS is on
stats = {"fired": 0, "skipped_steps": 0}
for _f in FAULTS:
    stats[_f] = 0


def enabled():
    """THE site guard: any programmatic rule, or MXNET_CHAOS set. One
    list check + one `_fastenv` read — the PR 2 off-cost budget."""
    if _prog:
        return True
    v = _fastenv.get("MXNET_CHAOS")
    return bool(v)


def _current_rules():
    """Programmatic rules + (cached) env-spec rules. The cache is keyed
    on the spec STRING so a monkeypatched env rebuilds, while an
    unchanged spec keeps its occurrence counters across calls."""
    global _env_spec, _env_rules
    spec = _fastenv.get("MXNET_CHAOS") or ""
    if spec != _env_spec:
        _env_rules = parse_spec(spec)
        _env_spec = spec
    return _prog + _env_rules


def rules():
    """Snapshot of the active rules (live objects — counters visible)."""
    with _lock:
        return list(_current_rules())


def _rank():
    from . import dist
    try:
        return dist.process_index()
    except Exception:
        return 0


def fire(site, **info):
    """Run the chaos checkpoint named ``site``. Executes every due
    matching rule's fault and returns the list of fault names fired
    (callers act on ``"nan"`` themselves). May sleep, raise
    ChaosError, SIGTERM the process, or _exit — by design."""
    return tuple(r.fault for r in fire_rules(site, **info))


def fire_rules(site, **info):
    """Like :func:`fire`, but returns the fired ``Rule`` objects —
    for sites that consume rule parameters (``bitflip``'s
    ``bit=``/``elem=``)."""
    if not enabled():
        return ()
    due = []
    with _lock:
        rs = _current_rules()
        rank = None
        for r in rs:
            if not r.matches(site):
                continue
            if r.rank is not None:
                if rank is None:
                    rank = _rank()
                if r.rank != rank:
                    continue
            if r.due():
                due.append(r)
                r.fired += 1
            r.seen += 1
        for r in due:
            stats["fired"] += 1
            stats[r.fault] += 1
    if not due:
        return ()
    if core.enabled():
        from . import flight as _flight
        for r in due:
            core.counter("chaos.injected").add(1)
            core.counter("chaos." + r.fault).add(1)
            core.record_instant(
                "chaos.inject", cat="chaos",
                args=dict(info, site=site, fault=r.fault,
                          occurrence=r.seen - 1))
            # the bundle must land BEFORE _execute: crash/sigterm
            # faults leave no later opportunity (per-cause capped, so
            # a retry loop of injected errors cannot flood the
            # sideband)
            _flight.record_incident(
                "chaos." + r.fault, site=site,
                occurrence=r.seen - 1,
                info={k: str(v) for k, v in info.items()})
    for r in due:
        _execute(r, site)
    return tuple(due)


def _execute(rule, site):
    if rule.fault == "delay":
        time.sleep((DEFAULT_DELAY_MS if rule.ms is None
                    else rule.ms) / 1e3)
    elif rule.fault == "hang":
        # blocks until release() or the (bounded) hang budget — a rank
        # that stopped dispatching, from the peers' point of view
        _release.wait((DEFAULT_HANG_MS if rule.ms is None
                       else rule.ms) / 1e3)
    elif rule.fault == "error":
        raise ChaosError(
            "chaos: injected fault at site %r (occurrence %d of rule %r)"
            % (site, rule.seen - 1, rule.pattern))
    elif rule.fault == "oom":
        # real-shaped RESOURCE_EXHAUSTED: the leading status text
        # matches what the PJRT allocator raises, so substring-matching
        # handlers treat the injection exactly like the real thing
        raise ChaosResourceExhausted(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate %d bytes. chaos: injected oom at site %r "
            "(occurrence %d of rule %r)"
            % (rule.bytes, site, rule.seen - 1, rule.pattern))
    elif rule.fault == "crash":
        os._exit(rule.code)          # SIGKILL semantics: no cleanup
    elif rule.fault == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
    # "nan" and "bitflip" have no side effect here: the caller owns
    # the value (cooperative corruption — see the poison_* helpers)


def release():
    """Unblock every in-flight ``hang`` fault (tests un-wedge the rank
    they hung)."""
    _release.set()


def inject(site, fault, **kw):
    """Install one programmatic rule; returns it (live counters)."""
    r = Rule(site, fault, **kw)
    with _lock:
        _prog.append(r)
    return r


def install(spec):
    """Install a whole spec string programmatically (the env grammar)."""
    rs = parse_spec(spec)
    with _lock:
        _prog.extend(rs)
    return rs


def reset():
    """Drop programmatic rules, forget the env-spec cache (counters
    restart), clear stats and the hang release latch."""
    global _env_spec, _env_rules
    with _lock:
        del _prog[:]
        _env_spec = None
        _env_rules = []
        for k in stats:
            stats[k] = 0
    _release.clear()


# ----------------------------------------------------- value poisoning --

def poison_ndarrays(site, arrays, **info):
    """Fire ``site`` and, if a ``nan`` rule was due, overwrite every
    float NDArray in ``arrays`` with NaN (a gradient gone bad). Returns
    True when poisoned. One guarded branch when chaos is off."""
    if not enabled():
        return False
    if "nan" not in fire(site, **info):
        return False
    import jax.numpy as jnp
    for a in arrays:
        data = getattr(a, "_data", None)
        if data is None or not jnp.issubdtype(data.dtype, jnp.floating):
            continue
        a._data = jnp.full_like(data, jnp.nan)
    return True


def _flip_in_array(data, bit, elem):
    """One flipped bit in a jax array: bitcast to the same-width uint,
    xor bit ``bit`` of element ``elem`` (both wrapped into range), and
    bitcast back — every other bit of every other element is
    untouched, so the corruption is exactly one bit wide."""
    import jax
    import jax.numpy as jnp
    flat = jnp.ravel(data)
    if flat.size == 0:
        return data
    utype = {1: jnp.uint8, 2: jnp.uint16,
             4: jnp.uint32, 8: jnp.uint64}.get(flat.dtype.itemsize)
    if utype is None:
        return data
    u = jax.lax.bitcast_convert_type(flat, utype)
    idx = int(elem) % flat.size
    mask = jnp.asarray(1, utype) << (int(bit) % (8 * flat.dtype.itemsize))
    u = u.at[idx].set(u[idx] ^ mask)
    return jax.lax.bitcast_convert_type(u, flat.dtype).reshape(
        data.shape)


def bitflip_array(site, arr, **info):
    """Fire ``site``; for every due ``bitflip`` rule, return ``arr``
    with bit ``rule.bit`` of element ``rule.elem`` flipped (a new
    array — jax arrays are immutable). Returns ``arr`` unchanged when
    nothing fired. One guarded branch when chaos is off."""
    if not enabled():
        return arr
    for r in fire_rules(site, **info):
        if r.fault == "bitflip":
            arr = _flip_in_array(arr, r.bit, r.elem)
    return arr


def poison_bitflip(site, arrays, **info):
    """Fire ``site``; for every due ``bitflip`` rule, flip one bit in
    place across the NDArray list — ``elem`` indexes the virtual
    concatenation of the arrays' flattened elements, so a spec can
    target any parameter of a whole tree deterministically. Returns
    True when a flip landed."""
    if not enabled():
        return False
    due = [r for r in fire_rules(site, **info) if r.fault == "bitflip"]
    if not due:
        return False
    arrays = [a for a in arrays if getattr(a, "_data", None) is not None]
    if not arrays:
        return False
    total = sum(int(a._data.size) for a in arrays)
    flipped = False
    for r in due:
        idx = r.elem % total if total else 0
        for a in arrays:
            n = int(a._data.size)
            if idx < n:
                a._data = _flip_in_array(a._data, r.bit, idx)
                flipped = True
                break
            idx -= n
    return flipped


def corrupt_bytes(site, data, **info):
    """Fire ``site``; for every due ``bitflip`` rule, return ``data``
    (bytes) with bit ``rule.bit`` of byte ``rule.elem`` flipped."""
    if not enabled():
        return data
    due = [r for r in fire_rules(site, **info) if r.fault == "bitflip"]
    if not due or not data:
        return data
    ba = bytearray(data)
    for r in due:
        ba[r.elem % len(ba)] ^= 1 << (r.bit % 8)
    return bytes(ba)


def corrupt_file(site, path, **info):
    """Fire ``site``; for every due ``bitflip`` rule, flip one bit of
    the file at ``path`` in place (byte ``rule.elem``, bit
    ``rule.bit``) — an at-rest corruption, e.g. a checkpoint byte
    rotting on disk. Returns True when a flip landed."""
    if not enabled():
        return False
    due = [r for r in fire_rules(site, path=str(path), **info)
           if r.fault == "bitflip"]
    if not due:
        return False
    flipped = False
    for r in due:
        try:
            with open(path, "r+b") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if not size:
                    continue
                off = r.elem % size
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ (1 << (r.bit % 8))]))
                flipped = True
        except OSError:
            continue
    return flipped


# --------------------------------------------------------- step guards --

def step_guard_enabled():
    """MXNET_STEP_GUARD=1 arms the Trainer/Module non-finite step
    guard. Off by default: the finiteness check syncs one scalar from
    device per step, a cost the un-armed hot path must not pay."""
    v = _fastenv.get("MXNET_STEP_GUARD")
    return v is not None and v not in ("", "0", "false", "False")


def all_finite(datas):
    """One device-side finiteness verdict over a list of jax arrays
    (floats checked, ints vacuously finite); a single bool syncs to
    host."""
    import jax.numpy as jnp
    verdicts = []
    for d in datas:
        if d is None:
            continue
        if jnp.issubdtype(jnp.asarray(d).dtype, jnp.floating):
            verdicts.append(jnp.all(jnp.isfinite(d)))
    if not verdicts:
        return True
    ok = verdicts[0]
    for v in verdicts[1:]:
        ok = jnp.logical_and(ok, v)
    return bool(ok)


def count_skipped_step(where, scaler=None):
    """Bookkeeping for one guarded (skipped) update: the always-on
    stats view, the obs counter/instant when recording, and the AMP
    loss-scale backoff when a scaler rides the trainer."""
    with _lock:
        stats["skipped_steps"] += 1
    if core.enabled():
        core.counter("chaos.skipped_steps").add(1)
        core.record_instant("chaos.step_skipped", cat="chaos",
                            args={"where": where})
    if scaler is not None:
        try:
            scaler.update_scale(True)    # overflow=True: back off
        except Exception:
            pass
