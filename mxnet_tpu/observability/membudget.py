"""HBM-pressure resilience — preflight memory budgeting + the OOM
taxonomy (ISSUE 14 tentpole).

Every failure class the robustness arc handles degrades gracefully
except one: an HBM allocation failure still kills the process outright.
The TensorFlow system paper (PAPERS.md) treats memory exhaustion as a
first-class scheduling signal rather than a fatal error, and the
cross-replica weight-update sharding paper shows per-replica memory is
a *tunable*. The two halves this module adds:

1. **Preflight budgeting.** The PR 4 attribution layer already computes
   per-executable ``memory_analysis()`` sizes and peak watermarks, and
   the PR 3 gauges already read live PJRT ``device_memory_stats`` —
   nothing consumed either before XLA's RESOURCE_EXHAUSTED did. With
   ``MXNET_MEM_BUDGET`` set, the first dispatch of every registered jit
   boundary (CachedOp fwd/step, Executor fwd/infer/bwd, the KVStore
   bucketed reduce, serving's decode/verify dispatch, paged-pool init)
   sums the executable's predicted peak (arguments + outputs − aliased
   + temps, max'd with the HLO def-to-last-use watermark) against live
   device headroom minus a ``MXNET_MEM_BUDGET_RESERVE_MB`` safety
   margin. A predicted breach surfaces *before* the device wedges:
   warn-only under ``MXNET_MEM_BUDGET=warn`` (or ``1``), a raised
   :class:`MemoryBudgetExceeded` — naming the executable, the predicted
   peak, the live headroom, and the top-3 scopes by watermark from the
   attribution breakdown — under ``MXNET_MEM_BUDGET=enforce``.

2. **OOM taxonomy + recovery.** The same boundaries classify a caught
   RESOURCE_EXHAUSTED as *transient-fragmentation* (a post-GC retry
   probe finds the headroom again) or *structural-overcommit* (the
   program cannot fit, full stop). ``MXNET_MEM_OOM_ACTION=accum`` lets
   a training loop re-lower its step through
   ``elastic.make_accum_train_step`` at 2× accumulation (global batch
   and loss trajectory preserved — the PR 9 elastic-accum bar;
   :func:`escalate_accum` refuses non-divisor factors loudly);
   ``=checkpoint`` routes through the PR 6 emergency provider and exits
   :data:`OOM_EXIT_CODE` (47) so ``tools/elastic_launch.py`` relaunches
   at the reduced setting (supervisor-side sticky
   ``MXNET_MEM_ACCUM_FACTOR``). Serving recovers in-process instead:
   the paged pool shrinks and the dispatch retries
   (``models/serving.py``).

The PR 6 ``async_save`` fix rides along: the D2H snapshot's in-flight
bytes were invisible to memory accounting — :func:`note_snapshot_start`
counts them against :func:`headroom_bytes`, and
:func:`admit_snapshot` defers (serializes) a snapshot that would itself
breach the reserve.

With every ``MXNET_MEM_*`` knob unset each hook is one guarded branch
(the PR 2 off-cost contract): dispatch counts and numerics stay
bit-identical — tested in tests/test_membudget.py.
"""

import os
import sys
import threading
import warnings

from . import core
from .. import _fastenv

__all__ = ["OOM_EXIT_CODE", "MemoryBudgetExceeded", "budget_mode",
           "enabled", "armed", "oom_action", "reserve_bytes",
           "sticky_accum_factor", "headroom_bytes", "device_headroom",
           "predicted_peak_bytes", "preflight", "preflight_bytes",
           "is_resource_exhausted", "classify_oom", "note_oom",
           "escalate_accum", "handle_trainer_oom", "checkpoint_and_exit",
           "note_snapshot_start", "note_snapshot_end",
           "snapshot_bytes_in_flight", "admit_snapshot",
           "healthz_snapshot", "stats", "reset"]

# supervisor-visible exit code (the taxonomy row next to 43 watchdog /
# 44 shrink / 45 boundary / 46 quarantine — docs/ROBUSTNESS.md): the
# worker hit structural memory overcommit, committed an emergency
# checkpoint, and asks elastic_launch to relaunch it at a reduced
# setting (sticky accumulation factor)
OOM_EXIT_CODE = 47

DEFAULT_RESERVE_MB = 64.0

_lock = threading.Lock()
_checked = set()          # (origin, signature) preflight verdicts issued
_snapshot_inflight = [0]  # bytes of D2H checkpoint snapshots in flight

# always-on cheap counters (the chaos.stats pattern); obs counters
# mirror them when MXNET_OBS is on
stats = {"preflight_checks": 0, "preflight_breaches": 0,
         "oom_caught": 0, "oom_transient": 0, "oom_structural": 0,
         "oom_accum": 0, "oom_checkpoint": 0, "snapshot_deferred": 0}


class MemoryBudgetExceeded(RuntimeError):
    """Preflight verdict: the executable's predicted peak does not fit
    the live device headroom (minus the reserve). Raised only under
    ``MXNET_MEM_BUDGET=enforce``; warn mode warns with the same text."""

    def __init__(self, origin, predicted, headroom, reserve, scopes):
        self.origin = origin
        self.predicted_bytes = int(predicted)
        self.headroom_bytes = int(headroom)
        self.reserve_bytes = int(reserve)
        self.scopes = dict(scopes or {})
        top = sorted(self.scopes.items(), key=lambda kv: -kv[1])[:3]
        msg = ("memory budget: %s predicts a %.1f MB peak against "
               "%.1f MB live headroom (reserve %.1f MB)"
               % (origin, predicted / 1e6, headroom / 1e6,
                  reserve / 1e6))
        if top:
            msg += "; top scopes by watermark: " + ", ".join(
                "%s=%.1fMB" % (s, b / 1e6) for s, b in top)
        super().__init__(msg)


# ------------------------------------------------------------- knobs --

def budget_mode():
    """``MXNET_MEM_BUDGET``: None (off) / ``"warn"`` (``warn``/``1``) /
    ``"enforce"``. One ``_fastenv`` read — THE preflight site guard."""
    v = _fastenv.get("MXNET_MEM_BUDGET")
    if not v or v in ("0", "false", "False"):
        return None
    return "enforce" if v == "enforce" else "warn"


def enabled():
    return budget_mode() is not None


def oom_action():
    """``MXNET_MEM_OOM_ACTION``: None / ``"accum"`` / ``"checkpoint"``
    — the training-side response to a classified OOM."""
    v = _fastenv.get("MXNET_MEM_OOM_ACTION")
    return v if v in ("accum", "checkpoint") else None


def armed():
    """True when ANY memory-pressure response is configured — the
    guard the OOM-classification hooks sit behind."""
    return enabled() or oom_action() is not None


def reserve_bytes():
    """``MXNET_MEM_BUDGET_RESERVE_MB`` safety margin (default 64 MB):
    headroom the budget refuses to promise — runtime scratch,
    fragmentation slack, the next allocation's breathing room."""
    try:
        mb = float(_fastenv.get("MXNET_MEM_BUDGET_RESERVE_MB",
                                DEFAULT_RESERVE_MB))
    except (TypeError, ValueError):
        mb = DEFAULT_RESERVE_MB
    return int(mb * 1e6)


def sticky_accum_factor():
    """``MXNET_MEM_ACCUM_FACTOR``: the supervisor-side sticky
    accumulation factor an exit-47 relaunch carries (default 1) —
    training loops start their step at this factor so the OOM that
    killed the previous generation is not re-lowered verbatim."""
    try:
        return max(int(_fastenv.get("MXNET_MEM_ACCUM_FACTOR", "1")
                       or 1), 1)
    except (TypeError, ValueError):
        return 1


# ---------------------------------------------------------- headroom --

def device_headroom():
    """Live per-device free HBM from the PJRT counters:
    {device: bytes_limit - bytes_in_use} for every device that reports
    both (CPU backends typically report neither)."""
    from .. import storage
    out = {}
    for dev, st in storage.device_memory_stats().items():
        if "bytes_limit" in st and "bytes_in_use" in st:
            out[dev] = int(st["bytes_limit"]) - int(st["bytes_in_use"])
    return out

def headroom_bytes():
    """The budget's denominator: the TIGHTEST device's free bytes minus
    the in-flight snapshot ledger (D2H staging the runtime has not
    surfaced in bytes_in_use yet). None when no device reports limits —
    every consumer treats unknown headroom as "stand down", never as
    infinite."""
    per = device_headroom()
    if not per:
        return None
    return min(per.values()) - _snapshot_inflight[0]


# ----------------------------------------------- snapshot byte ledger --

def note_snapshot_start(nbytes):
    """An async_save D2H snapshot of ``nbytes`` is in flight: count it
    against headroom until :func:`note_snapshot_end` (the PR 6 gap this
    PR closes — the snapshot used to be invisible to accounting)."""
    if not armed():
        return
    with _lock:
        _snapshot_inflight[0] += int(nbytes)
    if core.enabled():
        core.gauge("mem.snapshot_inflight_bytes", "bytes").set(
            _snapshot_inflight[0])


def note_snapshot_end(nbytes):
    if not armed():
        return
    with _lock:
        _snapshot_inflight[0] = max(_snapshot_inflight[0] - int(nbytes),
                                    0)
    if core.enabled():
        core.gauge("mem.snapshot_inflight_bytes", "bytes").set(
            _snapshot_inflight[0])


def snapshot_bytes_in_flight():
    return _snapshot_inflight[0]


def admit_snapshot(nbytes):
    """May an ``nbytes`` overlapped D2H snapshot start right now?
    False when the staging would itself breach the reserve — the caller
    defers to a leaf-by-leaf serial gather (peak = the largest leaf)
    instead of pushing a near-full device into the exact OOM the
    checkpoint insures against. Unknown headroom admits (the CPU mesh
    and platforms without stats keep the old behavior)."""
    hb = headroom_bytes()
    if hb is None:
        return True
    if int(nbytes) <= hb - reserve_bytes():
        return True
    stats["snapshot_deferred"] += 1
    if core.enabled():
        core.counter("mem.snapshot_deferred").add(1)
        core.record_instant(
            "mem.snapshot_deferred", cat="mem",
            args={"bytes": int(nbytes), "headroom": hb})
    return False


# ---------------------------------------------------------- preflight --

def predicted_peak_bytes(memory, watermark=0):
    """Predicted live-bytes peak of one executable from its
    ``memory_analysis()`` sizes: arguments + outputs − aliased
    (donated buffers are counted once) + temporaries, max'd against the
    HLO def-to-last-use watermark (which sees intra-program liveness
    the coarse sum cannot)."""
    memory = memory or {}
    total = (memory.get("argument_size_in_bytes", 0)
             + memory.get("output_size_in_bytes", 0)
             - memory.get("alias_size_in_bytes", 0)
             + memory.get("temp_size_in_bytes", 0))
    return max(int(total), int(watermark or 0))


def _signature_of(args):
    """A cheap structural key for the preflight cache when the caller
    has no recompile-detector signature: leaf shapes/dtypes."""
    import jax
    parts = []
    for leaf in jax.tree.leaves(args):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append("%s%s" % (leaf.dtype, tuple(leaf.shape)))
        else:
            parts.append(repr(leaf))
    return "|".join(parts)


def _memory_of(fn, args):
    """Lower + compile ``fn`` from the abstract signature of ``args``
    and return its ``memory_analysis()`` sizes (no registry entry
    needed; suppresses recompile events — this is analysis, not a
    retrace)."""
    from . import attribution, recompile
    aargs = attribution.abstract_args(args)
    with recompile.suppress_events():
        compiled = fn.lower(*aargs).compile()
    ma = compiled.memory_analysis()
    return {k: int(getattr(ma, k)) for k in
            ("argument_size_in_bytes", "output_size_in_bytes",
             "temp_size_in_bytes", "alias_size_in_bytes") if
            hasattr(ma, k)}


def _breach(origin, predicted, hb, scopes):
    stats["preflight_breaches"] += 1
    err = MemoryBudgetExceeded(origin, predicted, hb, reserve_bytes(),
                               scopes)
    if core.enabled():
        core.counter("mem.budget_breaches").add(1)
        core.record_instant(
            "mem.budget_breach", cat="mem",
            args={"origin": origin, "predicted_bytes": int(predicted),
                  "headroom_bytes": int(hb),
                  "mode": budget_mode()})
    if budget_mode() == "enforce":
        raise err
    warnings.warn(str(err), RuntimeWarning, stacklevel=3)


def preflight(origin, fn=None, args=None, signature=None):
    """The budget check a jit boundary runs before its FIRST dispatch
    of ``(origin, signature)``: predicted peak vs live headroom minus
    the reserve. Uses the PR 4 attribution registry's cached analysis
    when the program is registered there (which also names the top-3
    watermark scopes in a breach), lowering ``fn`` directly otherwise.
    Warm calls are one set-membership probe; with ``MXNET_MEM_BUDGET``
    unset callers never reach here (one guarded branch). Returns the
    predicted peak in bytes, or None when the check could not run
    (unknown headroom, no analyzable program)."""
    if budget_mode() is None:
        return None
    if signature is None and args is not None:
        signature = _signature_of(args)
    key = (origin, signature)
    if key in _checked:
        return None
    with _lock:
        if key in _checked:
            return None
        _checked.add(key)
    hb = headroom_bytes()
    if hb is None:
        return None         # platform reports no limits: stand down
    stats["preflight_checks"] += 1
    memory, watermark, scopes = None, 0, {}
    from . import attribution
    analysis = attribution.program_analysis(origin, signature)
    if analysis is not None and not analysis.get("error"):
        memory = analysis.get("memory") or {}
        watermark = analysis.get("peak_bytes", 0)
        scopes = analysis.get("peak_scopes") or {}
    elif fn is not None and args is not None:
        try:
            memory = _memory_of(fn, args)
        except Exception:    # backend without memory_analysis, etc.
            return None
    if not memory and not watermark:
        return None
    predicted = predicted_peak_bytes(memory, watermark)
    if core.enabled():
        core.gauge("mem.predicted_peak_bytes", "bytes").set(predicted)
    if predicted > hb - reserve_bytes():
        _breach(origin, predicted, hb, scopes)
    return predicted


def preflight_bytes(origin, nbytes, signature=None):
    """Direct-bytes preflight for allocations with a known size and no
    compiled program (paged-pool init/grow): same verdict path, same
    breach surface. Returns True when the allocation fits (or headroom
    is unknown)."""
    if budget_mode() is None:
        return True
    key = (origin, signature)
    with _lock:
        first = key not in _checked
        _checked.add(key)
    if not first:
        return True
    hb = headroom_bytes()
    if hb is None:
        return True
    stats["preflight_checks"] += 1
    if int(nbytes) > hb - reserve_bytes():
        _breach(origin, int(nbytes), hb, {})
        return False
    return True


def tree_nbytes(tree):
    """Total payload bytes of every leaf in a pytree — the direct-bytes
    cost a weight hot-swap must preflight (the incoming params are
    resident alongside the old set until the swap commits)."""
    import numpy as np
    from jax import tree_util
    total = 0
    for leaf in tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        n = 1
        for d in shape:
            n *= int(d)
        total += n * (np.dtype(dtype).itemsize if dtype is not None
                      else 8)
    return int(total)


# ------------------------------------------------------- OOM taxonomy --

def is_resource_exhausted(exc):
    """Does ``exc`` look like an XLA allocation failure? Matches the
    runtime's RESOURCE_EXHAUSTED status (XlaRuntimeError carries it in
    the message), generic out-of-memory texts, and the chaos layer's
    real-shaped injected fault — all three must route identically
    through the taxonomy."""
    if exc is None:
        return False
    text = "%s: %s" % (type(exc).__name__, exc)
    return ("RESOURCE_EXHAUSTED" in text
            or "ResourceExhausted" in text
            or "Out of memory" in text
            or "out of memory" in text)


def classify_oom(predicted=None):
    """The post-GC retry probe: drop dead host references (freeing
    their device buffers), re-read headroom, and judge — *transient*
    fragmentation when the freed headroom would now cover the demand
    (or, with no known demand, when any headroom above the reserve
    reappeared), *structural* overcommit otherwise. Structural is the
    verdict that justifies changing the program (accum re-lowering,
    pool shrink, exit 47); transient justifies a plain retry."""
    import gc
    gc.collect()
    hb = headroom_bytes()
    if hb is None:
        # no stats to probe with: assume the allocation is structural —
        # the conservative verdict (a retry that would have succeeded
        # costs one re-lower; a retry loop against a too-big program
        # costs the job)
        return "structural"
    if predicted is not None:
        fits = int(predicted) <= hb - reserve_bytes()
    else:
        fits = hb > reserve_bytes()
    return "transient" if fits else "structural"


def note_oom(origin, exc, predicted=None):
    """Classify a RESOURCE_EXHAUSTED caught at boundary ``origin``.
    No-op (None) when unarmed or for non-OOM errors — the except
    handlers this sits in stay one guarded branch off-path. Returns
    the taxonomy verdict string otherwise."""
    if not armed() or not is_resource_exhausted(exc):
        return None
    stats["oom_caught"] += 1
    verdict = classify_oom(predicted)
    stats["oom_" + verdict] += 1
    if core.enabled():
        core.counter("mem.oom_caught").add(1)
        core.counter("mem.oom_" + verdict).add(1)
        core.record_instant(
            "mem.oom", cat="mem",
            args={"origin": origin, "taxonomy": verdict,
                  "error": "%s: %s" % (type(exc).__name__, exc)})
    return verdict


def escalate_accum(accum, batch_rows, factor=2):
    """The ``MXNET_MEM_OOM_ACTION=accum`` response: the next
    accumulation factor (current × ``factor``) for re-lowering the
    step through ``elastic.make_accum_train_step`` — the same
    global-batch-preserving compensation PR 9 uses for shrinks. Refuses
    loudly when the global batch cannot tile the new factor: silently
    changing the effective batch is exactly the bug this knob
    prevents."""
    accum, batch_rows = int(accum), int(batch_rows)
    new = accum * int(factor)
    if batch_rows <= 0 or new <= 0:
        raise ValueError("escalate_accum needs positive sizes "
                         "(batch_rows=%d, accum=%d)" % (batch_rows,
                                                        accum))
    if batch_rows % new:
        raise ValueError(
            "MXNET_MEM_OOM_ACTION=accum: global batch of %d rows "
            "cannot tile an accumulation factor of %d — the OOM is "
            "structural at this batch geometry (reduce the batch or "
            "model instead)" % (batch_rows, new))
    stats["oom_accum"] += 1
    if core.enabled():
        core.counter("mem.oom_accum_relower").add(1)
        core.gauge("mem.accum_factor").set(new)
    return new


def checkpoint_and_exit(reason="oom"):
    """The ``MXNET_MEM_OOM_ACTION=checkpoint`` leg: commit through the
    PR 6 emergency provider (best-effort — an armed provider writes an
    exact-resume checkpoint, an unarmed one is skipped) and exit
    :data:`OOM_EXIT_CODE` so ``elastic_launch`` counts the restart and
    relaunches with the sticky accumulation factor doubled."""
    stats["oom_checkpoint"] += 1
    path = None
    try:
        from ..models import checkpoint as _ckpt
        path = _ckpt.save_emergency_checkpoint("oom:%s" % reason)
    except Exception:
        pass
    print("mxnet_tpu.membudget: %s — emergency checkpoint %s; "
          "exiting %d for the supervisor"
          % (reason, path or "not armed", OOM_EXIT_CODE),
          file=sys.stderr, flush=True)
    if core.enabled():
        core.counter("mem.oom_exit").add(1)
        core.record_instant("mem.oom_exit", cat="mem",
                            args={"reason": str(reason),
                                  "checkpoint": path})
    from . import flight as _flight
    _flight.record_incident("oom.structural", exit_code=OOM_EXIT_CODE,
                            reason=str(reason), checkpoint=path)
    raise SystemExit(OOM_EXIT_CODE)


def handle_trainer_oom(exc):
    """Trainer.step's except hook: classify a RESOURCE_EXHAUSTED and,
    under ``MXNET_MEM_OOM_ACTION=checkpoint``, route through the
    emergency provider + exit 47. The ``accum`` action cannot re-lower
    a Gluon trainer's update in place — the caller re-raises and the
    driving loop (or the supervisor restart with the sticky factor)
    owns the re-lowering. No-op for non-OOM errors / unarmed runs."""
    if not armed() or not is_resource_exhausted(exc):
        return
    verdict = note_oom("trainer.step", exc)
    if oom_action() == "checkpoint" and verdict == "structural":
        checkpoint_and_exit("trainer.step %s oom" % verdict)


# ------------------------------------------------------------ healthz --

def healthz_snapshot():
    """The /healthz ``mem`` section: live headroom (ledger applied),
    the reserve, in-flight snapshot bytes, and the cheap counters —
    what the router's starvation gate and an operator's dashboard
    read."""
    try:
        hb = headroom_bytes()
    except Exception:
        hb = None
    return {"headroom_bytes": hb,
            "reserve_bytes": reserve_bytes(),
            "snapshot_inflight_bytes": _snapshot_inflight[0],
            "oom_caught": stats["oom_caught"],
            "budget_mode": budget_mode() or "off"}


def predicted_step_ms(scope=None, signature=None, dirpath=None,
                      model=None):
    """Cost-model hook (ISSUE 18): the calibrated roofline prediction
    for an archived scope/signature, so admission decisions can weigh
    TIME next to bytes (a preflight that passes on memory but predicts
    a 10x step regression is still worth flagging). Per-admission
    callers are cheap: the archive load + fit go through
    ``costmodel.cached_fit`` (mtime/size-stamped memo, refit only when
    the archive changed on disk), and a caller holding its own prefit
    ``model`` can pass it in. Returns None when the performance
    archive is off or holds nothing for the workload — callers keep
    their bytes-only verdicts. Never raises."""
    try:
        from . import costmodel, profile_store
        if dirpath is None and not profile_store.enabled():
            return None
        records, cached_model = costmodel.cached_fit(dirpath)
        return costmodel.predict(signature=signature, scope=scope,
                                 records=records,
                                 model=model or cached_model)
    except Exception:
        return None


def reset():
    """Forget preflight verdicts + counters (tests, fresh sessions)."""
    with _lock:
        _checked.clear()
        _snapshot_inflight[0] = 0
        for k in stats:
            stats[k] = 0


# os is used by nothing else but keeps parity with sibling modules'
# exit paths should checkpoint_and_exit ever need _exit semantics
_ = os
