"""Log-bucketed latency histograms — the bounded-memory distribution
primitive the serving layer records into.

The PR 2 ``Counter`` keeps exact count/total/min/max but gets its
percentiles from the sample ring, so a long run's p99 is computed over
whatever suffix survived the ring — fine for step phases (thousands of
samples, all recent ones representative), wrong for per-request serving
latency where the SLO question is "p99 over the whole run". A
``Histogram`` trades exact values for EXACT-count log-spaced buckets:

* **bounded memory** — a fixed maximum of ``MAX_BUCKETS`` integer
  counts per histogram, grown lazily, never a per-sample record. A
  million observations cost the same bytes as a hundred.
* **bounded relative error** — bucket upper edges follow
  ``lo * growth**i`` (defaults: ``lo`` = 1e-3 ms, ``growth`` = 2**0.25
  ≈ 1.19), so any reported quantile is within one bucket — ≤ ~19%
  relative — of the true sample quantile, with linear interpolation
  inside the bucket doing better in practice. ``count``/``sum``/
  ``min``/``max`` stay exact.
* **mergeable** — two histograms with the same ``(lo, growth)`` merge
  bucket-wise (``merge_state``), which is how ``dist.merge_traces`` /
  ``tools/obs_merge.py`` combine per-rank serving distributions into
  fleet-level percentiles without ever shipping samples.
* **one guarded branch when off** — ``observe()`` returns after the
  ``core.enabled()`` check (the PR 2 contract); nothing allocates.

Knobs: ``MXNET_OBS_HIST_LO`` (lowest bucket upper edge, default 1e-3 —
values at/below it share bucket 0) and ``MXNET_OBS_HIST_GROWTH``
(bucket edge growth factor, default 2**0.25), both read at histogram
creation. Explicit ``lo=``/``growth=`` arguments beat the env.
"""

import math
import threading

from . import core
from .. import _fastenv

__all__ = ["Histogram", "histogram", "histograms", "states",
           "merge_state", "merge_state_maps", "reset", "MAX_BUCKETS",
           "DEFAULT_LO", "DEFAULT_GROWTH", "QUANTILES"]

# bucket 0 holds (-inf, lo]; bucket i>=1 holds (lo*g^(i-1), lo*g^i];
# the last bucket is open-ended. 192 buckets at the default growth
# cover 1e-3 .. ~1e11 ms — every latency this repo can produce.
MAX_BUCKETS = 192
DEFAULT_LO = 1e-3
DEFAULT_GROWTH = 2.0 ** 0.25

# the quantiles every exporter reports (p50/p90/p99/p99.9)
QUANTILES = ((0.50, "p50"), (0.90, "p90"), (0.99, "p99"),
             (0.999, "p999"))

_lock = threading.Lock()
_histograms = {}


class Histogram(object):
    """Thread-safe log-bucketed histogram; see the module docstring."""

    __slots__ = ("name", "unit", "lo", "growth", "_log_g", "counts",
                 "count", "sum", "min", "max")

    def __init__(self, name, unit="", lo=None, growth=None):
        self.name = name
        self.unit = unit
        self.lo = float(_fastenv.get("MXNET_OBS_HIST_LO", DEFAULT_LO)
                        if lo is None else lo)
        self.growth = float(_fastenv.get("MXNET_OBS_HIST_GROWTH",
                                         DEFAULT_GROWTH)
                            if growth is None else growth)
        if self.lo <= 0 or self.growth <= 1.0:
            raise ValueError("histogram needs lo > 0 and growth > 1 "
                             "(got lo=%g growth=%g)"
                             % (self.lo, self.growth))
        self._log_g = math.log(self.growth)
        self.counts = []
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    # ------------------------------------------------------ buckets --

    def _index(self, value):
        if value <= self.lo:
            return 0
        # ceil with a float-noise epsilon so an exact edge value lands
        # in the bucket it bounds (upper edges are inclusive)
        idx = int(math.ceil(math.log(value / self.lo) / self._log_g
                            - 1e-9))
        return min(max(idx, 1), MAX_BUCKETS - 1)

    def _upper(self, i):
        """Upper edge of bucket i (bucket 0's edge is ``lo``)."""
        return self.lo * self.growth ** i if i else self.lo

    def _lower(self, i):
        return self.lo * self.growth ** (i - 1) if i else 0.0

    # ---------------------------------------------------- recording --

    def observe(self, value):
        """Record one sample. A no-op (one guarded branch) when
        telemetry is off."""
        if not core.enabled():
            return
        value = float(value)
        idx = self._index(value) if value > 0 else 0
        with _lock:
            if idx >= len(self.counts):
                self.counts.extend([0] * (idx + 1 - len(self.counts)))
            self.counts[idx] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min,
                                                          value)
            self.max = value if self.max is None else max(self.max,
                                                          value)

    # ------------------------------------------------------ reading --

    def percentile(self, q):
        """Estimated q-quantile (q in [0, 1]): walk the cumulative
        bucket counts, interpolate linearly inside the landing bucket,
        clamp to the exact observed [min, max]."""
        with _lock:
            counts = list(self.counts)
            n, mn, mx = self.count, self.min, self.max
        if not n:
            return 0.0
        target = q * n
        cum = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= target:
                frac = (target - cum) / c
                val = self._lower(i) + \
                    (self._upper(i) - self._lower(i)) * frac
                return min(max(val, mn), mx)
            cum += c
        return mx

    def quantiles(self):
        return {label: self.percentile(q) for q, label in QUANTILES}

    def snapshot(self):
        """Exporter view: exact count/sum/min/max/mean + the standard
        quantile estimates."""
        out = {"count": self.count, "sum": self.sum,
               "min": self.min if self.min is not None else 0.0,
               "max": self.max if self.max is not None else 0.0,
               "mean": (self.sum / self.count) if self.count else 0.0,
               "unit": self.unit}
        out.update(self.quantiles())
        return out

    def cumulative_buckets(self):
        """[(upper_edge, cumulative_count)] over the populated prefix
        plus the +Inf total — the Prometheus histogram series."""
        with _lock:
            counts = list(self.counts)
            n = self.count
        out, cum = [], 0
        for i, c in enumerate(counts):
            cum += c
            out.append((self._upper(i), cum))
        out.append((float("inf"), n))
        return out

    # ------------------------------------------------- merge / state --

    def state(self):
        """The mergeable serialized form (rides the chrome trace's
        ``otherData.histograms`` so per-rank dumps can be combined
        bucket-wise)."""
        with _lock:
            return {"name": self.name, "unit": self.unit,
                    "lo": self.lo, "growth": self.growth,
                    "counts": list(self.counts), "count": self.count,
                    "sum": self.sum, "min": self.min, "max": self.max}

    @classmethod
    def from_state(cls, st):
        h = cls(st.get("name", ""), st.get("unit", ""),
                lo=st["lo"], growth=st["growth"])
        h.counts = [int(c) for c in st.get("counts", [])]
        h.count = int(st.get("count", 0))
        h.sum = float(st.get("sum", 0.0))
        h.min = st.get("min")
        h.max = st.get("max")
        return h

    def merge(self, other):
        """Fold ``other`` (Histogram or state dict) into self
        bucket-wise. Raises ValueError on (lo, growth) mismatch —
        bucket indices would not mean the same latency."""
        st = other.state() if isinstance(other, Histogram) else other
        if abs(st["lo"] - self.lo) > 1e-12 * self.lo \
                or abs(st["growth"] - self.growth) > 1e-9:
            raise ValueError(
                "cannot merge histograms with different bucketing: "
                "(lo=%g, growth=%g) vs (lo=%g, growth=%g)"
                % (self.lo, self.growth, st["lo"], st["growth"]))
        with _lock:
            counts = st.get("counts", [])
            if len(counts) > len(self.counts):
                self.counts.extend([0] * (len(counts)
                                          - len(self.counts)))
            for i, c in enumerate(counts):
                self.counts[i] += int(c)
            self.count += int(st.get("count", 0))
            self.sum += float(st.get("sum", 0.0))
            for key, pick in (("min", min), ("max", max)):
                v = st.get(key)
                if v is not None:
                    mine = getattr(self, key)
                    setattr(self, key,
                            v if mine is None else pick(mine, v))
        return self


def merge_state(a, b):
    """Bucket-wise merge of two state dicts -> a new state dict."""
    return Histogram.from_state(a).merge(b).state()


def merge_state_maps(maps):
    """Merge per-rank ``{name: state}`` maps (``merge_traces``'s
    histogram half). Returns ``(merged_map, conflicts)`` where
    ``conflicts`` lists names whose bucketing disagreed across ranks
    (first rank's state is kept for those)."""
    out, conflicts = {}, []
    for m in maps:
        for name, st in (m or {}).items():
            if name not in out:
                out[name] = dict(st)
                continue
            try:
                out[name] = merge_state(out[name], st)
            except ValueError:
                if name not in conflicts:
                    conflicts.append(name)
    return out, conflicts


# ------------------------------------------------------ registry -----

def histogram(name, unit="", lo=None, growth=None):
    """Get-or-create the named histogram (process-global registry,
    the ``core.counter`` pattern)."""
    h = _histograms.get(name)
    if h is None:
        with _lock:
            h = _histograms.get(name)
            if h is None:
                h = _histograms[name] = Histogram(name, unit, lo=lo,
                                                  growth=growth)
    return h


def histograms():
    """Snapshot of the registry (name -> Histogram)."""
    with _lock:
        return dict(_histograms)


def states():
    """{name: state dict} for every registered histogram — what the
    chrome trace exports and the cross-rank merge combines."""
    return {name: h.state() for name, h in sorted(histograms().items())}


def reset():
    """Clear the registry (tests, new profile sessions); called by
    ``core.reset()`` so one reset clears the whole telemetry state."""
    with _lock:
        _histograms.clear()
