"""Bounded time-series sampler + trend detectors over the PR 2 core.

Every gauge in the stack is last-value-only and every counter is a
running total — neither an operator nor the router can see a *trend*
(a KV-block leak, an SLO attainment slide before a rollback, a retrace
storm). This module closes that gap without unbounding memory: a
daemon tick every ``MXNET_OBS_TS_INTERVAL_MS`` (default 1000; 0
disables the thread, manual ``tick()`` still works) snapshots

* every counter/gauge's current value, and
* every histogram's per-window **delta** (observations and sum since
  the previous tick — the activity in the interval, not the lifetime
  total)

into fixed-size rings of ``MXNET_OBS_TS_WINDOW`` points (default 240 —
four minutes of history at the default interval). ``rates(name)``
derives per-second rates from a counter's ring (the numpy reference is
``np.diff(v) / np.diff(t) * 1e6``); ``last_window()`` is the
flight-recorder / aggregate-table export shape.

The PR 2 contract holds: with ``MXNET_OBS`` unset nothing here runs —
``maybe_start()`` is one guarded branch, no thread is created, no ring
is allocated.

The trend detectors at the bottom are pure functions over numeric
sequences with explicit thresholds — the router feeds them fleet
history, tests feed them synthetic series, and the thresholds are
policy (env-tunable at the call site), not code.
"""

import threading
import time

from . import core
from . import histogram as _hist
from .. import _fastenv

__all__ = ["DEFAULT_INTERVAL_MS", "DEFAULT_WINDOW", "interval_ms",
           "window", "tick", "ticks", "names", "series", "rates",
           "last_window", "maybe_start", "stop", "running", "reset",
           "slope", "detect_leak", "detect_slide", "detect_collapse",
           "detect_storm", "AnomalyWarning"]

DEFAULT_INTERVAL_MS = 1000
DEFAULT_WINDOW = 240


class AnomalyWarning(RuntimeWarning):
    """A fleet trend detector fired (KV leak, SLO slide, throughput
    collapse, retrace storm). Warned once per (detector, source) —
    the ``obs.anomaly.*`` counters track persistence."""

_lock = threading.Lock()
_series = {}              # name -> list ring of (t_us, value)
_kinds = {}               # name -> "counter" | "gauge" | "hist_count" | "hist_sum"
_heads = {}               # name -> next write index
_ticks = 0
_last_hist = {}           # hist name -> (count, sum) at previous tick
_thread = None
_stop = threading.Event()


def interval_ms():
    return int(float(_fastenv.get("MXNET_OBS_TS_INTERVAL_MS",
                                  DEFAULT_INTERVAL_MS)))


def window():
    return max(int(_fastenv.get("MXNET_OBS_TS_WINDOW", DEFAULT_WINDOW)),
               2)


def _push(name, kind, t_us, value, cap):
    ring = _series.get(name)
    if ring is None:
        ring = _series[name] = [None] * cap
        _kinds[name] = kind
        _heads[name] = 0
    h = _heads[name]
    ring[h % len(ring)] = (t_us, float(value))
    _heads[name] = h + 1


def tick(now_us=None):
    """One sampler tick: snapshot all counters/gauges + histogram
    deltas into the rings. Returns the tick's timestamp (us on the
    core trace timebase) or None when telemetry is off."""
    global _ticks
    if not core.enabled():
        return None
    t_us = core._now_us() if now_us is None else int(now_us)
    counters = core.counters()
    hstates = _hist.states()
    cap = window()
    with _lock:
        for name, c in counters.items():
            kind = "gauge" if isinstance(c, core.Gauge) else "counter"
            _push(name, kind, t_us, c.value, cap)
        for name, st in hstates.items():
            cnt = int(st.get("count", 0))
            tot = float(st.get("sum", 0.0))
            p_cnt, p_tot = _last_hist.get(name, (0, 0.0))
            _last_hist[name] = (cnt, tot)
            _push(name + ".win_count", "hist_count", t_us,
                  cnt - p_cnt, cap)
            _push(name + ".win_sum", "hist_sum", t_us, tot - p_tot, cap)
        _ticks += 1
    return t_us


def ticks():
    """Sampler ticks taken since the last reset()."""
    with _lock:
        return _ticks


def names():
    with _lock:
        return sorted(_series)


def series(name):
    """The ring for ``name``, oldest first: list of (t_us, value)."""
    with _lock:
        ring = _series.get(name)
        if ring is None:
            return []
        h = _heads[name]
        n = len(ring)
        if h <= n:
            return [p for p in ring[:h] if p is not None]
        return [p for p in ring[h % n:] + ring[:h % n] if p is not None]


def rates(name):
    """Per-second rates derived from a counter ring: successive
    ``(v1 - v0) / (t1 - t0 in s)``; one element shorter than the ring.
    numpy reference: ``np.diff(v) / np.diff(t) * 1e6``."""
    pts = series(name)
    out = []
    for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
        dt = t1 - t0
        out.append((v1 - v0) / (dt / 1e6) if dt > 0 else 0.0)
    return out


def last_window():
    """Export shape for the flight recorder and the aggregate table:
    every ring's points plus derived rates for counters."""
    out = {"interval_ms": interval_ms(), "window": window(),
           "ticks": ticks(), "series": {}}
    for name in names():
        pts = series(name)
        ent = {"kind": _kinds.get(name, "gauge"),
               "t_us": [t for t, _v in pts],
               "values": [v for _t, v in pts]}
        if ent["kind"] == "counter":
            ent["rate_per_s"] = rates(name)
        out["series"][name] = ent
    return out


def _run():                            # pragma: no cover - thread body
    while not _stop.wait(max(interval_ms(), 1) / 1000.0):
        try:
            tick()
        except Exception:              # noqa: BLE001 — sampler never dies
            pass


def maybe_start():
    """Start the daemon sampler thread if telemetry is on and the
    interval is nonzero. Idempotent; one guarded branch when off."""
    global _thread
    if not core.enabled() or interval_ms() <= 0:
        return False
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop.clear()
        _thread = threading.Thread(target=_run, daemon=True,
                                   name="mxnet-obs-ts")
        _thread.start()
    return True


def running():
    with _lock:
        return _thread is not None and _thread.is_alive()


def stop(timeout=2.0):
    """Stop the daemon thread (tests, profiler teardown)."""
    global _thread
    t = _thread
    if t is None:
        return
    _stop.set()
    t.join(timeout)
    with _lock:
        _thread = None


def reset():
    """Forget every ring and the histogram-delta baseline (tests)."""
    global _ticks
    with _lock:
        _series.clear()
        _kinds.clear()
        _heads.clear()
        _last_hist.clear()
        _ticks = 0


# ---------------------------------------------------------------------
# trend detectors — pure functions, thresholds are the caller's policy
# ---------------------------------------------------------------------

def slope(values):
    """Least-squares slope of ``values`` against their indices
    (numpy reference: ``np.polyfit(range(n), values, 1)[0]``)."""
    n = len(values)
    if n < 2:
        return 0.0
    sx = (n - 1) * n / 2.0
    sxx = (n - 1) * n * (2 * n - 1) / 6.0
    sy = float(sum(values))
    sxy = float(sum(i * v for i, v in enumerate(values)))
    denom = n * sxx - sx * sx
    if denom == 0:
        return 0.0
    return (n * sxy - sx * sy) / denom


def detect_leak(free_blocks, occupancy, min_points=8, min_drop=1.0):
    """KV-block leak at idle: over a window where the replica held NO
    work (every occupancy sample zero), its free-block gauge still
    trended down by at least ``min_drop`` blocks. Free blocks falling
    under load is normal; falling while idle means blocks left the
    pool and never came back."""
    if len(free_blocks) < min_points or len(occupancy) < min_points:
        return False
    if any(o > 0 for o in occupancy):
        return False
    return (slope(free_blocks) < 0
            and free_blocks[0] - free_blocks[-1] >= min_drop)


def _head_tail_means(values):
    q = max(len(values) // 4, 1)
    head = values[:q]
    tail = values[-q:]
    return sum(head) / len(head), sum(tail) / len(tail)


def detect_slide(values, drop=0.2, min_points=8):
    """SLO attainment slide: the window's tail-quarter mean fell at
    least ``drop`` (fraction) below its head-quarter mean — the shape
    that precedes a post-swap rollback."""
    if len(values) < min_points:
        return False
    head, tail = _head_tail_means(values)
    return head > 0 and tail <= head * (1.0 - drop)


def detect_collapse(values, drop=0.5, min_points=8):
    """Throughput collapse: same head/tail comparison as the slide
    detector but for rate-like series, with a deeper default drop —
    half the window's opening throughput gone by its close."""
    return detect_slide(values, drop=drop, min_points=min_points)


def detect_storm(deltas, threshold=3):
    """Retrace storm: at least ``threshold`` recompiles landed inside
    the window (``deltas`` are per-tick recompile-count increments —
    steady state after warmup is zero)."""
    return sum(deltas) >= threshold
