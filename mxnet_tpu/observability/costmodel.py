"""Calibrated roofline cost model over the performance archive.

The PR 4 attribution layer already derives analytic flops/HBM-bytes
per scope; the roofline bound (``flops/peak`` vs ``bytes/bw``) is a
*shape* of the truth but not a clock — real kernels land at some
achieved fraction of peak that differs per scope family. This module
closes the gap the way TVM's learned cost model does, but with the
cheapest learner that works: fit the archived measurements
(observability/profile_store.py) against the two roofline terms by
least squares, per scope family, and report how well the fit explains
the data (median relative error = the calibration error).

    model = costmodel.fit()                  # from MXNET_OBS_PROFILE_DIR
    costmodel.predict(scope="paged_decode_kernel")   # -> predicted ms
    costmodel.predict(flops=f, hbm_bytes=b)          # -> predicted ms

Fit form per family: ``ms ~= a * flops_ms + b * bytes_ms + c`` where
``flops_ms = flops / peak_flops * 1e3`` and ``bytes_ms = hbm_bytes /
hbm_bw * 1e3`` (peaks from the attribution roofline knobs
``MXNET_OBS_OPS_PEAK_FLOPS`` / ``MXNET_OBS_OPS_HBM_GBS``). With fewer
than 3 points a single achieved-fraction scale ``ms ~= alpha *
max(flops_ms, bytes_ms)`` is fitted instead; a family with no
archived points falls back to the global fit.

Consumers: ``export.aggregate_table()`` / ``tools/obs_ops.py`` append
the predicted-vs-measured calibration table (worst-calibrated scopes
named — a bad fit means the analytic model is missing traffic, the
autotuner pre-flight signal); ``kernels/common.choose_block_k``
consults ``archived_block_k()`` so a measured winner beats the static
heuristic; ``membudget.predicted_step_ms`` exposes the prediction to
admission decisions. All entry points are no-ops returning None/[]
when the archive is off or empty, and never raise.
"""

import math
import os
import threading

from . import profile_store

__all__ = ["fit", "cached_fit", "predict", "predict_ms",
           "calibration_report", "format_calibration_table",
           "archived_block_k", "reset_cache"]

MIN_LSQ_POINTS = 3       # below this, fit the single-scale model
_EPS = 1e-9

_cache_lock = threading.Lock()
_fit_cache = [None]      # (stamp, records, model)


def _peaks():
    from . import attribution
    return attribution.peak_flops(), attribution.hbm_bw()


def _roofline_terms(flops, hbm_bytes, peak_flops, hbm_bw):
    """(flops_ms, bytes_ms): the two analytic time terms."""
    return (1e3 * float(flops or 0) / max(peak_flops, _EPS),
            1e3 * float(hbm_bytes or 0) / max(hbm_bw, _EPS))


def _points(records):
    """Measured (family, scope, sig, flops_ms, bytes_ms, measured_ms)
    tuples from scope records that carry both a timing and an
    attribution estimate."""
    peak_flops, hbm_bw = _peaks()
    pts = []
    for r in records:
        if r.get("kind") != "scope":
            continue
        stats = r.get("stats") or {}
        y = stats.get("p50_ms")
        if not y or y <= 0:
            continue
        flops, hbm = r.get("flops", 0), r.get("hbm_bytes", 0)
        if not flops and not hbm:
            continue
        f_ms, b_ms = _roofline_terms(flops, hbm, peak_flops, hbm_bw)
        pts.append((profile_store.normalize_scope(r.get("scope", "")),
                    r.get("scope", ""), r.get("sig", ""),
                    f_ms, b_ms, float(y)))
    return pts


def _fit_points(pts):
    """Fit one family's points -> model dict with kind 'lsq' (normal
    least squares over [flops_ms, bytes_ms, 1]) or 'scale' (achieved
    fraction of the roofline bound) plus its calibration error."""
    if not pts:
        return None
    ys = [p[5] for p in pts]
    if len(pts) >= MIN_LSQ_POINTS:
        try:
            import numpy as np
            X = np.array([[p[3], p[4], 1.0] for p in pts])
            y = np.array(ys)
            coef, _res, _rank, _sv = np.linalg.lstsq(X, y, rcond=None)
            model = {"kind": "lsq", "coef": [float(c) for c in coef],
                     "n": len(pts)}
        except Exception:
            model = None
        if model is not None:
            model["calib_err"] = _calib_err(model, pts)
            return model
    ratios = sorted(p[5] / max(max(p[3], p[4]), _EPS) for p in pts)
    alpha = ratios[len(ratios) // 2]
    model = {"kind": "scale", "alpha": float(alpha), "n": len(pts)}
    model["calib_err"] = _calib_err(model, pts)
    return model


def predict_ms(model, flops_ms, bytes_ms):
    """Apply one fitted family model to the two roofline terms."""
    if model is None:
        return None
    if model["kind"] == "lsq":
        a, b, c = model["coef"]
        return max(a * flops_ms + b * bytes_ms + c, 0.0)
    return model["alpha"] * max(flops_ms, bytes_ms)


def _calib_err(model, pts):
    """Median relative error of the fit over its own points."""
    errs = sorted(abs((predict_ms(model, p[3], p[4]) or 0) - p[5])
                  / max(p[5], _EPS) for p in pts)
    return errs[len(errs) // 2] if errs else float("inf")


def fit(records=None, dirpath=None, exclude_scope=None):
    """Fit per-family models (+ a global fallback) against the archive.
    ``exclude_scope`` holds one normalized scope out of the fit (the
    held-out calibration check). Returns {"families": {...}, "global":
    model-or-None, "n": points} — {"families": {}, "global": None,
    "n": 0} when the archive is off/empty."""
    if records is None:
        records, _ev = profile_store.load(dirpath)
    pts = _points(records)
    if exclude_scope:
        held = profile_store.normalize_scope(exclude_scope)
        pts = [p for p in pts if p[0] != held]
    fams = {}
    for p in pts:
        fams.setdefault(p[0], []).append(p)
    return {"families": {fam: _fit_points(fpts)
                         for fam, fpts in sorted(fams.items())},
            "global": _fit_points(pts), "n": len(pts)}


def _archive_stamp(dirpath=None):
    """Cheap change stamp of the archive dir: (path, mtime_ns, size)
    per file. Appends grow the size, prune's os.replace bumps the
    mtime — either invalidates the cache. None when the store is
    off."""
    d = dirpath or profile_store.store_dir()
    if not d:
        return None
    stamp = [d]
    for p in profile_store.list_files(d):
        try:
            st = os.stat(p)
            stamp.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            stamp.append((p, -1, -1))
    return tuple(stamp)


def cached_fit(dirpath=None):
    """(records, model) memoized on the archive's mtime/size stamp —
    the hot-caller entry point (membudget's per-admission
    ``predicted_step_ms``), which must not pay a full archive reload +
    lstsq refit per call when nothing changed on disk."""
    stamp = _archive_stamp(dirpath)
    if stamp is None:
        return [], fit(records=[])
    with _cache_lock:
        hit = _fit_cache[0]
        if hit is not None and hit[0] == stamp:
            return hit[1], hit[2]
    records, _ev = profile_store.load(dirpath)
    model = fit(records=records)
    with _cache_lock:
        _fit_cache[0] = (stamp, records, model)
    return records, model


def reset_cache():
    """Drop the cached_fit memo (tests)."""
    with _cache_lock:
        _fit_cache[0] = None


def predict(signature=None, scope=None, flops=None, hbm_bytes=None,
            model=None, records=None, dirpath=None):
    """Predicted per-call ms for a workload.

    Identify the workload by its archive signature key, by scope name,
    or by explicit ``flops``/``hbm_bytes``. When flops/bytes are not
    given they come from the newest archived record matching the
    signature/scope. Returns None when the workload is unknown or the
    archive holds nothing to fit — a caller that gets None falls back
    to its own heuristic."""
    if records is None:
        records, _ev = profile_store.load(dirpath)
    if model is None:
        model = fit(records=records)
    fam = None
    if flops is None and hbm_bytes is None:
        match = None
        for r in reversed(records):     # newest last (load sorts by ts)
            if r.get("kind") != "scope":
                continue
            if signature is not None and r.get("sig") == signature:
                match = r
                break
            if (scope is not None and match is None and
                    profile_store.normalize_scope(r.get("scope", ""))
                    == profile_store.normalize_scope(scope)):
                match = r
                if signature is None:
                    break
        if match is None:
            return None
        flops = match.get("flops", 0)
        hbm_bytes = match.get("hbm_bytes", 0)
        fam = profile_store.normalize_scope(match.get("scope", ""))
    elif scope is not None:
        fam = profile_store.normalize_scope(scope)
    elif signature is not None:
        fam = signature.split("|", 1)[0]
    peak_flops, hbm_bw = _peaks()
    f_ms, b_ms = _roofline_terms(flops, hbm_bytes, peak_flops, hbm_bw)
    m = model["families"].get(fam) if fam else None
    if m is None:
        m = model["global"]
    return predict_ms(m, f_ms, b_ms)


def calibration_report(records=None, dirpath=None):
    """Per-scope predicted-vs-measured rows, worst-calibrated first:
    [{"scope", "sig", "predicted_ms", "measured_ms", "calib_err",
    "n"}]. Empty when the archive is off or holds no usable points."""
    if records is None:
        records, _ev = profile_store.load(dirpath)
    model = fit(records=records)
    if not model["n"]:
        return []
    peak_flops, hbm_bw = _peaks()
    newest = {}
    for r in records:               # load() sorts by ts: last wins
        if r.get("kind") == "scope" and (r.get("stats") or {}).get(
                "p50_ms"):
            newest[r.get("sig", "")] = r
    rows = []
    for sig, r in sorted(newest.items()):
        flops, hbm = r.get("flops", 0), r.get("hbm_bytes", 0)
        if not flops and not hbm:
            continue
        fam = profile_store.normalize_scope(r.get("scope", ""))
        m = model["families"].get(fam) or model["global"]
        if m is None:
            continue
        f_ms, b_ms = _roofline_terms(flops, hbm, peak_flops, hbm_bw)
        measured = float(r["stats"]["p50_ms"])
        predicted = predict_ms(m, f_ms, b_ms)
        rows.append({"scope": fam, "sig": sig,
                     "predicted_ms": predicted,
                     "measured_ms": measured,
                     "calib_err": m["calib_err"], "n": m["n"]})
    rows.sort(key=lambda r: (-r["calib_err"], r["scope"]))
    return rows


def format_calibration_table(records=None, dirpath=None):
    """The aggregate-table section: predicted vs measured per scope
    with the fit's calibration error, worst-calibrated scopes named.
    [] when the archive is off/empty (the section simply disappears
    from ``profiler.dumps(aggregate=True)``). Never raises."""
    try:
        if records is None and dirpath is None \
                and not profile_store.enabled():
            return []
        rows = calibration_report(records=records, dirpath=dirpath)
    except Exception:
        return []
    if not rows:
        return []
    fmt = "%-36s %14s %14s %10s %7s"
    lines = ["", "Cost model calibration (performance archive)",
             "=" * 10,
             fmt % ("Scope", "Predicted(ms)", "Measured(ms)",
                    "CalibErr", "Points")]
    for r in rows:
        lines.append(fmt % (r["scope"][:36],
                            "%.3f" % (r["predicted_ms"] or 0),
                            "%.3f" % r["measured_ms"],
                            "%.0f%%" % (100 * r["calib_err"]),
                            r["n"]))
    worst = [r["scope"] for r in rows[:3] if r["calib_err"] > 0.25]
    if worst:
        lines.append("  worst-calibrated: %s (analytic model missing "
                     "traffic?)" % ", ".join(worst))
    return lines


def archived_block_k(t_max, multiple=1,
                     families=("paged_decode_kernel",
                               "paged_verify_kernel"),
                     dirpath=None):
    """The measured block_k winner for the paged decode-kernel scope
    families, from COMPARABLE measurements only. Archived kernel-scope
    records are grouped by (scope family, normalized program
    signature) — the config fingerprint is deliberately excluded from
    the group key, since it encodes the MXNET_PAGED_BLOCK_K knob being
    compared — and a winner must come from ONE group holding >= 2
    distinct candidates that tile this ``t_max`` (an actual measured
    A/B on the same workload shape): a block_k measured only on small
    paged workloads must not win a pooled median and get applied to a
    much larger cache, and flash_decode (which does not honor the
    paged knob) is out of the default families. Within the
    best-evidenced group (most distinct candidates, then most
    measurements) each candidate scores by its median measured p50;
    the fastest wins. None when no group holds a comparable A/B — the
    caller keeps its static heuristic. The predict-and-prune entry
    point ROADMAP item 5 deferred."""
    records, _ev = profile_store.load(dirpath)
    groups = {}
    for r in records:
        if r.get("kind") != "scope":
            continue
        fam = profile_store.normalize_scope(r.get("scope", ""))
        if fam not in families:
            continue
        y = (r.get("stats") or {}).get("p50_ms")
        raw = (r.get("config") or {}).get("env", {}).get(
            "MXNET_PAGED_BLOCK_K")
        if not y or not raw:
            continue
        try:
            bk = int(raw)
        except ValueError:
            continue
        if bk <= 0 or bk % multiple or t_max % bk or bk > t_max:
            continue
        key = (fam, profile_store.normalize_signature(
            r.get("signature", "")))
        groups.setdefault(key, {}).setdefault(bk, []).append(float(y))
    best_rank, best_by_bk = None, None
    for key, by_bk in sorted(groups.items()):
        if len(by_bk) < 2:      # one candidate is not a comparison
            continue
        rank = (len(by_bk), sum(len(v) for v in by_bk.values()))
        if best_rank is None or rank > best_rank:
            best_rank, best_by_bk = rank, by_bk
    if best_by_bk is None:
        return None
    best, best_ms = None, math.inf
    for bk, ys in sorted(best_by_bk.items()):
        ys.sort()
        med = ys[len(ys) // 2]
        if med < best_ms:
            best, best_ms = bk, med
    return best


_ = os   # parity with sibling modules' env-driven exit paths
