"""Live scrape endpoint — a background HTTP thread serving the current
telemetry instead of waiting for an on-dump textfile.

The PR 2 Prometheus exporter only writes at ``profiler.dump()`` time,
so a live run is invisible until someone dumps. With
``MXNET_OBS_HTTP=<port>`` set (and telemetry on) a daemon thread serves:

* ``GET /metrics``  — the Prometheus exposition text, rendered fresh
  per scrape (counters, gauges, span summaries, the log-bucketed
  ``serving.*`` histograms with per-bucket series and quantiles).
* ``GET /healthz``  — a JSON snapshot for load-balancer/router health
  probes: rank, uptime, lane occupancy and the other gauges, histogram
  quantiles, SLO attainment — the per-replica load signal the
  ROADMAP-1 router consumes.

The server starts lazily (first instrumented ``ContinuousBatcher``,
``profiler.set_state('run')`` or ``profiler.dump()``) via
``maybe_start()``, binds once per process, and never takes the
telemetry hot path: every scrape reads the same snapshots the
exporters use. A failed bind (port taken) warns once and stays off —
observability must never take serving down. Multi-process runs on one
host should point each rank at its own port; ``/healthz`` reports the
rank so a scraper can label the target.

``start(port)`` / ``stop()`` are the programmatic API (tests bind port
0 for an ephemeral port; ``port()`` reports the bound one).
"""

import json
import os
import threading
import time
import warnings

from . import core
from .. import _fastenv

__all__ = ["start", "stop", "maybe_start", "port"]

_lock = threading.Lock()
_server = None
_thread = None
_t0 = time.time()
_failed = False


def _healthz():
    """The /healthz JSON snapshot (also what tests assert on)."""
    from . import dist, export, slo
    from . import events as _ev
    from . import flight as _flight
    from . import timeseries as _ts
    from . import histogram as _hist
    agg = export.aggregate()
    try:
        from . import membudget
        mem = membudget.healthz_snapshot()
    except Exception:  # noqa: BLE001 — health must never 500
        mem = {}
    try:
        from . import goodput as _goodput
        gp = _goodput.healthz_snapshot()
    except Exception:  # noqa: BLE001
        gp = {}
    return {
        "status": "ok",
        "rank": dist.process_index(),
        "num_processes": dist.process_count(),
        "pid": os.getpid(),
        "enabled": core.enabled(),
        "uptime_s": time.time() - _t0,
        "dropped_records": core.dropped(),
        "counters": {name: s["value"]
                     for name, s in agg["counters"].items()},
        "histograms": {name: {k: h[k] for k in
                              ("count", "mean", "p50", "p90", "p99",
                               "p999", "max")}
                       for name, h in agg["histograms"].items()},
        "slo": {"targets": dict(slo.targets()),
                "attainment": slo.attainment()},
        "mem": mem,
        "goodput": gp,
        "events": {"depth": _ev.depth(), "dropped": _ev.dropped(),
                   "kinds": _ev.counts()},
        "flight": {"last_incident": _flight.last_incident(),
                   "incidents": _flight.incidents_written()},
        "anomalies": {name[len("obs.anomaly."):]: s["value"]
                      for name, s in agg["counters"].items()
                      if name.startswith("obs.anomaly.")},
        "timeseries": {"ticks": _ts.ticks(),
                       "series": len(_ts.names()),
                       "sampler_running": _ts.running()},
    }


def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            try:
                if path == "/metrics":
                    from . import export
                    body = export.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/healthz":
                    body = (json.dumps(_healthz(), indent=1)
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/":
                    body = (b"mxnet_tpu.observability scrape endpoint\n"
                            b"/metrics  prometheus exposition\n"
                            b"/healthz  JSON health snapshot\n")
                    ctype = "text/plain"
                else:
                    self.send_error(404, "unknown path %r" % path)
                    return
            except Exception as exc:   # never take the scraper down
                self.send_error(500, "snapshot failed: %s" % exc)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):   # scrapes must not spam stderr
            pass

    return Handler


def start(port):
    """Bind and serve on a daemon thread; idempotent (returns the
    already-bound port on a second call). ``port=0`` binds an
    ephemeral port — the return value is always the real one."""
    global _server, _thread
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        from http.server import ThreadingHTTPServer
        _server = ThreadingHTTPServer(("0.0.0.0", int(port)),
                                      _make_handler())
        _thread = threading.Thread(target=_server.serve_forever,
                                   name="mxnet-obs-http", daemon=True)
        _thread.start()
        # a live-scraped process wants trends, not just last values:
        # kick the bounded time-series sampler (no-op when obs is off)
        from . import timeseries as _ts
        _ts.maybe_start()
        return _server.server_address[1]


def maybe_start():
    """Start the endpoint iff MXNET_OBS_HTTP names a port and no server
    is up yet. A bind failure warns once and disables further attempts
    — the scrape endpoint is best-effort, serving is not."""
    global _failed
    if _server is not None or _failed:
        return port()
    v = _fastenv.get("MXNET_OBS_HTTP")
    if not v or v in ("0", "false", "False"):
        return None
    try:
        return start(int(v))
    except Exception as exc:
        _failed = True
        warnings.warn("mxnet_tpu.observability: MXNET_OBS_HTTP=%s "
                      "endpoint failed to start (%s); continuing "
                      "without live scrape" % (v, exc),
                      RuntimeWarning, stacklevel=2)
        return None


def port():
    """The bound port, or None when the server is down."""
    with _lock:
        return _server.server_address[1] if _server else None


def stop():
    """Shut the endpoint down (tests; production lets the daemon thread
    die with the process)."""
    global _server, _thread, _failed
    with _lock:
        srv, thr = _server, _thread
        _server = _thread = None
        _failed = False
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if thr is not None:
        thr.join(timeout=5)
