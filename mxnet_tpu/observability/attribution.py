"""Per-operator attribution — named-scope propagation + compiled-program
cost/memory breakdown (ISSUE 4 tentpole).

The reference framework's defining observability feature was the
per-operator profiler (``profiler.set_config(profile_all=True)`` emitted
one lane per executed op). On this stack the executed unit is a fused
XLA program, so per-op attribution has two halves:

1. **Scope propagation** (write side). When telemetry is on, Gluon
   ``Block.__call__`` binds ``jax.named_scope(block.name)`` around
   forward and ``executor.build_graph_fn`` binds
   ``jax.named_scope(node.name)`` around every symbol node's primitive
   emission. XLA preserves those frames as ``op_name`` metadata on every
   optimized (even fused) instruction, so each instruction names the
   block that produced it. Off, both sites reduce to one guarded branch
   (the PR 2 contract); scope names reach the HLO only if telemetry was
   on when the program was TRACED.

2. **Program breakdown** (read side). The instrumented jit boundaries
   (CachedOp, Executor) register each distinct executable here — the
   jitted callable plus the abstract ``ShapeDtypeStruct`` signature, no
   device buffers held — and the recompile detector's backend-compile
   events invalidate stale analyses. On demand (profiler.dump, the
   aggregate table, tools/obs_ops.py) each program is lowered and its
   optimized HLO parsed (``observability.hlo``): per-instruction flops /
   HBM bytes / output bytes grouped by source scope, plus a
   def-to-last-use peak-watermark attribution, cached per executable.

Reporting: ``format_ops_table()`` (appended to
``profiler.dumps(aggregate=True)``) ranks scopes by estimated roofline
time share; ``publish_counters()`` exports ``ops.<scope>.flops`` /
``ops.<scope>.hbm_bytes`` gauges through the normal chrome-trace /
Prometheus paths; ``summary()`` is the JSON the perf-regression
sentinel (``tools/obs_regression.py``) diffs against a committed
baseline; ``compare_summaries()`` is the diff itself.

Knobs: ``MXNET_OBS_OPS`` (default on when MXNET_OBS is on) gates both
halves; ``MXNET_OBS_OPS_TOPK`` table depth;
``MXNET_OBS_OPS_PEAK_FLOPS`` / ``MXNET_OBS_OPS_HBM_GBS`` set the
roofline used for the bound/share columns.
"""

import threading

from . import core
from . import hlo
from .. import _fastenv

__all__ = ["ops_enabled", "note_scope", "known_scopes", "register_program",
           "needs_program", "abstract_args", "on_compile", "analyses",
           "program_analysis",
           "summary", "format_ops_table", "publish_counters",
           "compare_summaries", "reset", "DEFAULT_TOLERANCES"]

_MAX_PROGRAMS = 64
UNATTRIBUTED = "(unattributed)"

_lock = threading.Lock()
_scopes = set()          # named scopes stamped at trace time
_programs = {}           # (origin, signature) -> entry dict, insertion order


def ops_enabled():
    """Master gate for scope propagation + breakdown: telemetry on AND
    MXNET_OBS_OPS not disabled (default on)."""
    if not core.enabled():
        return False
    v = _fastenv.get("MXNET_OBS_OPS", "1")
    return v not in ("", "0", "false", "False")


def topk():
    return int(_fastenv.get("MXNET_OBS_OPS_TOPK", 10))


def peak_flops():
    """Roofline compute peak (flop/s) for the bound/share columns;
    default matches the v5e bf16 dense peak the LM bench uses."""
    return float(_fastenv.get("MXNET_OBS_OPS_PEAK_FLOPS", 197e12))


def hbm_bw():
    """Roofline HBM bandwidth (bytes/s); default 819 GB/s (v5e)."""
    return float(_fastenv.get("MXNET_OBS_OPS_HBM_GBS", 819)) * 1e9


def note_scope(name):
    """Record a named scope stamped at trace time (the read side only
    attributes op_name components it saw the runtime emit)."""
    if name and name not in _scopes:
        with _lock:
            _scopes.add(name)


def known_scopes():
    with _lock:
        return set(_scopes)


# --------------------------------------------------- program registry --

def abstract_args(tree):
    """The args pytree with every array leaf reduced to its aval —
    holds shapes/dtypes for a later ``fn.lower``, never buffers."""
    import jax

    def leaf(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            sharding = getattr(a, "sharding", None)
            if sharding is not None:
                # keep the sharding so a mesh program (the kvstore's
                # bucketed reduce) re-lowers to the SAME collective
                try:
                    return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=sharding)
                except TypeError:
                    pass
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a
    return jax.tree.map(leaf, tree)


def needs_program(origin, signature):
    """True until ``register_program`` has seen (origin, signature) —
    lets call sites skip building analysis closures on the warm path."""
    return (origin, signature) not in _programs


def register_program(origin, signature, fn, args):
    """An instrumented jit boundary (CachedOp.__call__, Executor
    forward/backward) reporting the executable it is about to run.
    Idempotent per (origin, signature) — one dict probe on the warm
    path. ``args`` are the live call arguments; only their abstract
    signature is retained."""
    key = (origin, signature)
    if key in _programs:
        return
    with _lock:
        if key in _programs:
            return
        while len(_programs) >= _MAX_PROGRAMS:
            _programs.pop(next(iter(_programs)))
        _programs[key] = {"origin": origin, "signature": signature,
                          "fn": fn, "abstract_args": abstract_args(args),
                          "analysis": None}


def on_compile(origin, kind):
    """Recompile-detector hook: a fresh XLA executable was built —
    any cached analysis for that origin is stale."""
    if kind != "backend_compile":
        return
    with _lock:
        for (org, _sig), ent in _programs.items():
            if origin is None or org == origin:
                ent["analysis"] = None


def _analyze(entry):
    """Lower + compile the registered program from its abstract
    signature and break the optimized HLO down per scope. Lowering
    re-traces (the live executable is not reachable through public
    jax API), so this runs only at report time and is cached."""
    from . import recompile
    fn, args = entry["fn"], entry["abstract_args"]
    with recompile.suppress_events():
        compiled = fn.lower(*args).compile()
    text = compiled.as_text()
    # no runtime-registered scopes (a raw-jax program like the kvstore
    # reduce or a bench's hand-built step): fall back to the heuristic
    # op_name path split so the table still names source structure
    known = known_scopes() or None
    rows = hlo.attribute_rows(hlo.parse_hlo(text), known)
    scopes, totals = hlo.group_by_scope(rows,
                                        unattributed=UNATTRIBUTED)
    peak, peak_scopes = hlo.peak_watermark(rows,
                                           unattributed=UNATTRIBUTED)
    analysis = {
        "origin": entry["origin"], "signature": entry["signature"],
        "scopes": scopes, "totals": totals,
        "peak_bytes": peak, "peak_scopes": peak_scopes,
        "xla_cost": hlo.compiled_cost(compiled),
    }
    try:
        ma = compiled.memory_analysis()
        analysis["memory"] = {
            k: int(getattr(ma, k)) for k in
            ("argument_size_in_bytes", "output_size_in_bytes",
             "temp_size_in_bytes", "alias_size_in_bytes",
             "generated_code_size_in_bytes") if hasattr(ma, k)}
    except Exception:
        analysis["memory"] = {}
    return analysis


def analyses(refresh=False):
    """Per-program breakdowns for every registered executable (computed
    lazily, cached until the next backend compile for the origin)."""
    with _lock:
        entries = list(_programs.values())
    out = []
    for entry in entries:
        if entry["analysis"] is None or refresh:
            try:
                entry["analysis"] = _analyze(entry)
            except Exception as exc:     # backend without as_text, etc.
                entry["analysis"] = {
                    "origin": entry["origin"],
                    "signature": entry["signature"],
                    "scopes": {}, "totals": {}, "peak_bytes": 0,
                    "peak_scopes": {}, "error": str(exc)}
        out.append(entry["analysis"])
    return out


def program_analysis(origin, signature=None):
    """The cached breakdown for ONE registered executable — the memory
    budget's preflight source (``membudget.preflight`` reads
    ``memory`` / ``peak_bytes`` / ``peak_scopes`` from it). Exact
    (origin, signature) when the caller has the recompile-detector
    signature, else the first entry for ``origin``. None when the
    program was never registered; computes (and caches) the analysis on
    first use, same as :func:`analyses`."""
    with _lock:
        entry = _programs.get((origin, signature))
        if entry is None:
            for (org, _sig), ent in _programs.items():
                if org == origin:
                    entry = ent
                    break
    if entry is None:
        return None
    if entry["analysis"] is None:
        try:
            entry["analysis"] = _analyze(entry)
        except Exception as exc:         # backend without as_text, etc.
            entry["analysis"] = {
                "origin": entry["origin"],
                "signature": entry["signature"],
                "scopes": {}, "totals": {}, "peak_bytes": 0,
                "peak_scopes": {}, "error": str(exc)}
    return entry["analysis"]


# ----------------------------------------------------------- summary --

def summary(refresh=False):
    """Aggregate across every registered program: overall totals plus
    per-scope flops / HBM bytes / counts — the sentinel's unit of
    comparison. Peak-watermark attribution comes from the program with
    the highest peak (the step's memory high-water mark)."""
    per = [a for a in analyses(refresh) if not a.get("error")]
    scopes = {}
    totals = {"flops": 0.0, "hbm_bytes": 0, "out_bytes": 0, "count": 0,
              "attributed_flops": 0.0, "attributed_hbm_bytes": 0,
              "programs": len(per)}
    peak_prog = None
    for a in per:
        t = a["totals"]
        for k in ("flops", "hbm_bytes", "out_bytes", "count",
                  "attributed_flops", "attributed_hbm_bytes"):
            totals[k] += t.get(k, 0)
        for scope, ent in a["scopes"].items():
            dst = scopes.setdefault(scope, {"count": 0, "flops": 0.0,
                                            "hbm_bytes": 0,
                                            "out_bytes": 0})
            for k in dst:
                dst[k] += ent.get(k, 0)
        if peak_prog is None or a["peak_bytes"] > peak_prog["peak_bytes"]:
            peak_prog = a
    totals["peak_bytes"] = peak_prog["peak_bytes"] if peak_prog else 0
    return {"totals": totals, "scopes": scopes,
            "peak_scopes": dict(peak_prog["peak_scopes"])
            if peak_prog else {},
            "programs": [{"origin": a["origin"],
                          "signature": a["signature"],
                          "totals": a["totals"],
                          "peak_bytes": a["peak_bytes"]} for a in per]}


def _ranked(scopes):
    """Scopes ranked by estimated roofline time (the resource each is
    actually bound by), heaviest first."""
    pf, bw = peak_flops(), hbm_bw()

    def t_est(ent):
        return max(ent["flops"] / pf, ent["hbm_bytes"] / bw)
    return sorted(scopes.items(), key=lambda kv: -t_est(kv[1])), t_est


def format_ops_table(summ=None, k=None):
    """The per-scope top-K table as text lines — appended to
    ``profiler.dumps(aggregate=True)`` after the counter/skew sections.
    Empty when no compiled program has been registered."""
    if summ is None:
        if not _programs:
            return []
        summ = summary()
    scopes = summ.get("scopes") or {}
    if not scopes:
        return []
    k = topk() if k is None else k
    ranked, t_est = _ranked(scopes)
    t_total = sum(t_est(e) for _, e in ranked) or 1.0
    pf = peak_flops()
    totals = summ["totals"]
    fmt = "%-44s %6s %10s %10s %8s %5s %6s %6s"
    lines = ["",
             "Per-operator attribution (%d program%s, top %d scopes by "
             "roofline time)" % (totals.get("programs", 0),
                                 "" if totals.get("programs") == 1
                                 else "s", min(k, len(ranked))),
             "=" * 26,
             fmt % ("Scope", "Instrs", "GFLOP", "HBM MB", "FLOP/B",
                    "Bound", "Time%", "MFU%")]
    for scope, ent in ranked[:k]:
        ai = ent["flops"] / max(ent["hbm_bytes"], 1)
        t = t_est(ent)
        bound = "mxu" if ent["flops"] / pf >= ent["hbm_bytes"] / hbm_bw() \
            else "hbm"
        mfu = ent["flops"] / (t_total * pf)
        lines.append(fmt % (
            scope[-44:], ent["count"], "%.3f" % (ent["flops"] / 1e9),
            "%.2f" % (ent["hbm_bytes"] / 1e6), "%.1f" % ai, bound,
            "%.1f" % (100.0 * t / t_total), "%.2f" % (100.0 * mfu)))
    att_f = totals.get("attributed_flops", 0.0)
    att_b = totals.get("attributed_hbm_bytes", 0)
    lines.append(
        "  attributed: %.1f%% of %.3f GFLOP, %.1f%% of %.2f MB HBM; "
        "peak watermark %.2f MB"
        % (100.0 * att_f / max(totals.get("flops", 0.0), 1e-9),
           totals.get("flops", 0.0) / 1e9,
           100.0 * att_b / max(totals.get("hbm_bytes", 0), 1),
           totals.get("hbm_bytes", 0) / 1e6,
           totals.get("peak_bytes", 0) / 1e6))
    return lines


def publish_counters(summ=None):
    """Export the per-scope numbers as ``ops.<scope>.flops`` /
    ``ops.<scope>.hbm_bytes`` gauges — they ride the existing ring ->
    chrome-trace / Prometheus paths. Called by ``profiler.dump()``."""
    if not core.enabled() or not _programs:
        return
    summ = summary() if summ is None else summ
    for scope, ent in summ["scopes"].items():
        core.gauge("ops.%s.flops" % scope).set(ent["flops"])
        core.gauge("ops.%s.hbm_bytes" % scope).set(ent["hbm_bytes"])
    core.gauge("ops.peak_bytes").set(summ["totals"].get("peak_bytes", 0))


# ---------------------------------------------------------- sentinel --

DEFAULT_TOLERANCES = {"flops": 0.15, "hbm_bytes": 0.15,
                      "out_bytes": 0.25, "peak_bytes": 0.25,
                      "count": 0.5}


def compare_summaries(baseline, current, tolerances=None):
    """Diff a run's attribution summary against a committed baseline.

    A metric REGRESSES when ``current > baseline * (1 + tol)`` —
    checked on the aggregate totals and per-scope flops/hbm_bytes.
    Returns {"regressions": [...], "improvements": [...],
    "notes": [...]}; the sentinel exits nonzero iff regressions is
    non-empty. Scopes present only on one side produce notes (renames /
    structure changes), not failures — the aggregate totals still catch
    real growth hiding behind a rename.
    """
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    regressions, improvements, notes = [], [], []

    def check(path, metric, base, cur):
        t = tol.get(metric, 0.15)
        if base is None or cur is None:
            return
        base = float(base)
        cur = float(cur)
        if cur > base * (1.0 + t) + 1e-9:
            regressions.append(
                {"where": path, "metric": metric, "baseline": base,
                 "current": cur,
                 "ratio": cur / base if base else float("inf"),
                 "tolerance": t})
        elif base > 0 and cur < base * (1.0 - t):
            improvements.append(
                {"where": path, "metric": metric, "baseline": base,
                 "current": cur, "ratio": cur / base})

    bt = baseline.get("totals", {})
    ct = current.get("totals", {})
    for metric in ("flops", "hbm_bytes", "out_bytes", "peak_bytes"):
        check("totals", metric, bt.get(metric), ct.get(metric))
    bs = baseline.get("scopes", {})
    cs = current.get("scopes", {})
    for scope in sorted(set(bs) | set(cs)):
        if scope not in cs:
            notes.append("scope %r in baseline but not in current run "
                         "(renamed or removed)" % scope)
            continue
        if scope not in bs:
            notes.append("scope %r new in current run" % scope)
            continue
        for metric in ("flops", "hbm_bytes"):
            check("scope:%s" % scope, metric, bs[scope].get(metric),
                  cs[scope].get(metric))
    return {"regressions": regressions, "improvements": improvements,
            "notes": notes}


def reset():
    """Forget scopes + registered programs (tests, fresh sessions)."""
    with _lock:
        _scopes.clear()
        _programs.clear()
