"""Persistent performance archive: per-scope profile records that
outlive the process.

Everything the observability stack measures today dies at exit — the
PR 2 span rings, the PR 4 per-scope flops/bytes attribution, the bench
headline rows — so ``obs_regression`` can only diff against one
hand-committed snapshot and ROADMAP item 5's deferred autotuner has no
measured data to learn from. This module is the substrate both need
(the TVM learned-cost-model pattern): an append-only, CRC-framed,
per-host archive of (workload signature -> measured cost) records
under ``MXNET_OBS_PROFILE_DIR``.

On-disk form (house MXFLIGHT-style framing, many frames per file):

    MXPROF1 <crc32> <len>\\n{ json record }\\n

Files are ``profiles.<host>.mxp``, opened O_APPEND so concurrent
writers interleave whole frames (writers additionally serialize on a
sidecar ``.lock`` flock so the retention rewrite cannot discard a
concurrent append); the reader re-synchronizes on the
magic and skips torn/corrupt frames with named evidence
(``torn-header`` / ``bad-magic`` / ``torn-payload`` / ``crc-mismatch``
/ ``bad-json``) carrying the file + byte offset — a crash mid-write
costs one record, never the archive.

Records are keyed by a STABLE workload signature: the normalized scope
name (trailing ``_<n>`` rename counters stripped), the normalized
PR 4 registered-executable signature (the leading/batch axis of every
rank>=2 shape wildcarded, so a re-jit with a widened batch keeps the
same key), and a config fingerprint (device kind, mesh/process shape,
and the perf-relevant env knobs in ``FINGERPRINT_ENVS``). Each record
carries the measured span stats (count/total/p50/p99 from the PR 2
rings), attributed flops/HBM bytes, and a run id.

Writers: ``record_run()`` (hooked into ``profiler.dump()``) archives
one record per scope; ``append_bench()`` (benchmark/common.py) archives
headline bench rows. Retention is per signature
(``MXNET_OBS_PROFILE_KEEP`` newest records each, default 32).
Readers: ``load()`` -> (records, evidence), ``merge_by_signature()``
joins runs into one timeline per signature — what
``tools/perf_timeline.py`` renders and ``obs_regression --history``
guards.

Off-path contract (PR 2): with ``MXNET_OBS_PROFILE_DIR`` unset every
entry point is ONE guarded branch (`enabled()` is a ~0.1us _fastenv
read) and no store I/O happens at all.
"""

import contextlib
import hashlib
import json
import os
import re
import socket
import threading
import time
import zlib

try:
    import fcntl
except ImportError:        # non-POSIX: intra-process _lock only
    fcntl = None

from .. import _fastenv

__all__ = ["MAGIC", "SCHEMA", "StoreError", "FINGERPRINT_ENVS",
           "enabled", "store_dir", "keep", "history", "run_id",
           "config_fingerprint", "archived_device_doc", "normalize_scope",
           "normalize_signature", "signature_key", "frame",
           "read_file", "load", "append", "append_bench",
           "record_run", "prune", "merge_by_signature", "runs_in",
           "run_series", "host_file", "list_files", "reset"]

MAGIC = b"MXPROF1"
SCHEMA = 1

ENV_DIR = "MXNET_OBS_PROFILE_DIR"
ENV_KEEP = "MXNET_OBS_PROFILE_KEEP"
ENV_HISTORY = "MXNET_OBS_PROFILE_HISTORY"
ENV_RUN = "MXNET_OBS_PROFILE_RUN"

DEFAULT_KEEP = 32        # newest records kept per signature
DEFAULT_HISTORY = 8      # rolling window obs_regression --history uses

# the perf-relevant knobs baked into the config fingerprint: records
# measured under different kernel/serving configs must never merge
# into one timeline (a block_k A/B is two signatures, not noise)
FINGERPRINT_ENVS = (
    "MXNET_PAGED_DECODE_PALLAS",
    "MXNET_PAGED_BLOCK_K",
    "MXNET_KV_BLOCK_SIZE",
    "MXNET_KV_PAGED",
    "MXNET_SPEC_K",
    "MXNET_FLASH_BLOCK_Q",
    "MXNET_FLASH_BLOCK_K",
    "MXNET_FLASH_STAT_LANES",
    "MXNET_OBS_OPS_PEAK_FLOPS",
    "MXNET_OBS_OPS_HBM_GBS",
)

_lock = threading.Lock()
_run = [None]            # per-process generated run id
_device_doc = [None]     # cached device/mesh half of the fingerprint


class StoreError(ValueError):
    """A torn or corrupt frame, with named evidence (the flight
    recorder's BundleError discipline)."""

    def __init__(self, evidence, detail=""):
        self.evidence = evidence
        self.detail = detail
        super(StoreError, self).__init__("%s: %s" % (evidence, detail))


# ------------------------------------------------------- gating/env ---

def enabled():
    """THE off-path guard: one ~0.1us dict read. Every public writer
    returns immediately when this is False."""
    return bool(_fastenv.get(ENV_DIR))


def store_dir(create=False):
    d = _fastenv.get(ENV_DIR)
    if not d:
        return None
    if create and not os.path.isdir(d):
        try:
            os.makedirs(d)
        except OSError:
            pass
    return d


def _int_env(name, default, floor):
    try:
        return max(int(_fastenv.get(name, default)), floor)
    except (TypeError, ValueError):
        return default


def keep():
    """Per-signature retention cap (MXNET_OBS_PROFILE_KEEP)."""
    return _int_env(ENV_KEEP, DEFAULT_KEEP, 1)


def history():
    """Rolling-window size for --history (MXNET_OBS_PROFILE_HISTORY)."""
    return _int_env(ENV_HISTORY, DEFAULT_HISTORY, 1)


def run_id():
    """This process's run id: MXNET_OBS_PROFILE_RUN when set (benches /
    CI name their runs), else a generated ``r<unixtime>-p<pid>`` that
    stays stable for the process lifetime so a workload dumped twice
    still reads as one run."""
    explicit = _fastenv.get(ENV_RUN)
    if explicit:
        return explicit
    with _lock:
        if _run[0] is None:
            _run[0] = "r%d-p%d" % (int(time.time()), os.getpid())
        return _run[0]


def _host():
    try:
        h = socket.gethostname() or "host"
    except Exception:
        h = "host"
    return re.sub(r"[^A-Za-z0-9_.-]+", "-", h)


def host_file(dirpath):
    return os.path.join(dirpath, "profiles.%s.mxp" % _host())


def list_files(dirpath):
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return []
    return [os.path.join(dirpath, n) for n in names
            if n.startswith("profiles.") and n.endswith(".mxp")]


# ------------------------------------------------ workload signature ---

# 'f32[8,128]' / 'bf16[4,16,64]{shard}' shape tokens: wildcard the
# leading (batch) axis of every rank>=2 shape so a re-jit with a
# widened batch keeps the signature; rank-1 shapes (param vectors,
# length tables) stay exact — their size IS the workload.
_SHAPE_RE = re.compile(r"([A-Za-z0-9_]+)\[(\d+)((?:,\d+)+)\]")

# jax/Block naming counters: 'dense_1', 'paged_decode_kernel_2' are
# renames of the same scope, not new workloads
_RENAME_RE = re.compile(r"(?:_\d+)+$")


def normalize_signature(sig):
    """Stable form of a PR 4 registered-executable signature: the
    leading dim of every rank>=2 shape token becomes ``*``."""
    if not sig:
        return ""
    return _SHAPE_RE.sub(lambda m: "%s[*%s]" % (m.group(1), m.group(3)),
                         str(sig))


def normalize_scope(name):
    """Stable form of a scope name: trailing ``_<n>`` rename counters
    and any bracketed shape suffix stripped."""
    if not name:
        return ""
    base = str(name).split("[", 1)[0]
    norm = _RENAME_RE.sub("", base)
    return norm or base


_UNKNOWN_DEVICE_DOC = {"device_kind": "?", "backend": "?",
                       "n_devices": 0, "n_processes": 0}
_DEVICE_DOC_KEYS = tuple(_UNKNOWN_DEVICE_DOC)


def archived_device_doc(dirpath=None):
    """The device half of the fingerprint from the NEWEST archived
    record that carries one — written by a process that actually held
    the device — or None. Never touches a backend."""
    records, _ev = load(dirpath)
    for r in reversed(records):                 # load() sorts by ts
        cfg = r.get("config") or {}
        if cfg.get("device_kind") and cfg.get("device_kind") != "?":
            return {k: cfg.get(k) for k in _DEVICE_DOC_KEYS}
    return None


def config_fingerprint(extra=None, discover=True):
    """(fingerprint-id, doc): device kind + mesh/process shape + the
    FINGERPRINT_ENVS knobs, hashed to a short id. The doc rides in
    every record so a timeline can explain why two signatures differ.
    Device discovery is cached per process and best-effort (the store
    must work before/without a backend).

    ``discover=False`` NEVER initializes a backend: the device doc
    comes from the newest archived record (written by the process that
    measured it), else the unknown-device placeholder. This is for
    orchestrators like ``benchmark/run_chip_queue.py`` whose contract
    is that one leg subprocess at a time exclusively claims the chip —
    a ``jax.devices()`` in the parent would hold the claim and starve
    every later leg. The placeholder is not cached, so the doc
    upgrades to the real one once a leg has archived it."""
    doc = _device_doc[0]
    if doc is None:
        if discover:
            try:
                import jax
                dev = jax.devices()[0]
                doc = {"device_kind": getattr(dev, "device_kind", "?"),
                       "backend": jax.default_backend(),
                       "n_devices": jax.device_count(),
                       "n_processes": jax.process_count()}
            except Exception:
                doc = dict(_UNKNOWN_DEVICE_DOC)
            _device_doc[0] = doc
        else:
            doc = archived_device_doc()
            if doc is not None:
                _device_doc[0] = doc
            else:
                doc = dict(_UNKNOWN_DEVICE_DOC)
    cfg = dict(doc)
    cfg["env"] = {k: os.environ[k] for k in FINGERPRINT_ENVS
                  if os.environ.get(k)}
    if extra:
        cfg["extra"] = extra
    blob = json.dumps(cfg, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha1(blob).hexdigest()[:12], cfg


def signature_key(scope, signature="", fingerprint=""):
    """The stable archive key: normalized scope | normalized program
    signature | config fingerprint id."""
    return "|".join((normalize_scope(scope),
                     normalize_signature(signature),
                     fingerprint or ""))


# --------------------------------------------------------- framing ---

def frame(doc):
    """CRC-frame one record dict -> bytes (one line-oriented frame; the
    trailing newline keeps the file greppable)."""
    body = json.dumps(doc, sort_keys=True, default=str).encode("utf-8")
    head = b"%s %08x %d\n" % (MAGIC, zlib.crc32(body) & 0xFFFFFFFF,
                              len(body))
    return head + body + b"\n"


def read_file(path):
    """Parse one archive file -> (records, evidence). Torn or corrupt
    frames are SKIPPED, each leaving one evidence dict naming the file,
    byte offset and what was wrong; the reader re-synchronizes on the
    next magic so one bad frame never hides the rest."""
    records, evidence = [], []

    def note(offset, kind, detail):
        evidence.append({"file": path, "offset": int(offset),
                         "evidence": kind, "detail": detail})

    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as exc:
        note(0, "unreadable", str(exc))
        return records, evidence
    pos, n = 0, len(data)
    while pos < n:
        idx = data.find(MAGIC, pos)
        if idx < 0:
            if data[pos:].strip():
                note(pos, "bad-magic", repr(data[pos:pos + 32]))
            break
        if idx > pos and data[pos:idx].strip():
            note(pos, "bad-magic", repr(data[pos:idx][:32]))
        nl = data.find(b"\n", idx)
        if nl < 0:
            note(idx, "torn-header", "no newline in %d trailing bytes"
                 % (n - idx))
            break
        parts = data[idx:nl].split()
        want_crc = want_len = None
        if len(parts) == 3:
            try:
                want_crc, want_len = int(parts[1], 16), int(parts[2])
            except ValueError:
                pass
        if want_len is None:
            note(idx, "bad-magic", repr(data[idx:nl][:64]))
            pos = idx + len(MAGIC)
            continue
        body = data[nl + 1:nl + 1 + want_len]
        if len(body) < want_len:
            note(idx, "torn-payload", "expected %d body bytes, found %d"
                 % (want_len, len(body)))
            break
        pos = nl + 1 + want_len
        if (zlib.crc32(body) & 0xFFFFFFFF) != want_crc:
            note(idx, "crc-mismatch", "expected %08x, computed %08x"
                 % (want_crc, zlib.crc32(body) & 0xFFFFFFFF))
            continue
        try:
            records.append(json.loads(body.decode("utf-8")))
        except ValueError as exc:
            note(idx, "bad-json", str(exc))
    return records, evidence


def load(dirpath=None):
    """All records across the archive dir's per-host files ->
    (records sorted by ts, evidence list)."""
    d = dirpath or store_dir()
    records, evidence = [], []
    if not d:
        return records, evidence
    for path in list_files(d):
        recs, ev = read_file(path)
        records.extend(recs)
        evidence.extend(ev)
    records.sort(key=lambda r: r.get("ts", 0))
    return records, evidence


# --------------------------------------------------------- writers ---

@contextlib.contextmanager
def _file_lock(path):
    """Cross-process writer lock: flock on a sidecar ``<file>.lock``
    (never the data file itself — prune's os.replace swaps the data
    inode, which would orphan a lock taken on it). O_APPEND alone makes
    concurrent appends safe, but prune's read-modify-replace is not:
    a frame appended between its read and its replace would be
    silently discarded, so every writer — append AND prune — holds
    this lock. Best-effort: without fcntl (non-POSIX) or on lock
    errors, fall back to the intra-process ``_lock`` the callers
    already hold."""
    if fcntl is None:
        yield
        return
    try:
        fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:
        yield
        return
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
        except OSError:
            pass
        yield
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        except OSError:
            pass
        os.close(fd)


def append(doc, dirpath=None):
    """Append one framed record to this host's archive file. Returns
    the path, or None when the store is off (the guarded branch) or
    the write fails — archiving must never break the workload."""
    if dirpath is None:
        if not enabled():
            return None
        dirpath = store_dir(create=True)
    elif not os.path.isdir(dirpath):
        try:
            os.makedirs(dirpath)
        except OSError:
            return None
    if not dirpath:
        return None
    path = host_file(dirpath)
    data = frame(doc)
    try:
        with _lock, _file_lock(path):
            with open(path, "ab") as f:     # O_APPEND: whole frames
                f.write(data)
                f.flush()
    except OSError:
        return None
    return path


def _span_stats(s):
    return {"count": s["count"], "total_ms": s["total_ms"],
            "p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"]}


def record_run(run=None, dirpath=None, ts=None):
    """Archive the current telemetry ring + attribution scopes: one
    record per scope name seen by either, keyed by the stable workload
    signature. Called from ``profiler.dump()`` behind ``enabled()``;
    never raises, returns the number of records written."""
    try:
        if dirpath is None and not enabled():
            return 0
        from . import export as _export
        spans = _export.aggregate()["spans"]
        scopes, progsigs = {}, {}
        try:
            from . import attribution as _attr
            analyses = _attr.analyses()
            for a in analyses:
                for scope in a.get("scopes", {}):
                    progsigs.setdefault(scope, a.get("signature") or "")
            if analyses:
                scopes = _attr.summary().get("scopes", {})
        except Exception:
            scopes, progsigs = {}, {}
        fid, cfg = config_fingerprint()
        run = run or run_id()
        ts = time.time() if ts is None else ts
        wrote = 0
        for name in sorted(set(spans) | set(scopes)):
            a = scopes.get(name, {})
            rec = {"schema": SCHEMA, "kind": "scope", "run": run,
                   "ts": ts, "host": _host(), "scope": name,
                   "sig": signature_key(name, progsigs.get(name, ""),
                                        fid),
                   "signature": normalize_signature(
                       progsigs.get(name, "")),
                   "fingerprint": fid, "config": cfg,
                   "stats": (_span_stats(spans[name])
                             if name in spans else None),
                   "flops": a.get("flops", 0),
                   "hbm_bytes": a.get("hbm_bytes", 0)}
            if append(rec, dirpath=dirpath) is not None:
                wrote += 1
        if wrote:
            prune(dirpath=dirpath)
        return wrote
    except Exception:
        return 0


def append_bench(leg, value=None, unit=None, metric=None, extra=None,
                 dirpath=None, run=None, fingerprint=None, config=None):
    """Archive one bench headline row (benchmark/common.py's hook).
    ``fingerprint``/``config`` let a caller that already computed the
    fingerprint (run_chip_queue's orchestrator, which must not trigger
    device discovery) pass it through instead of recomputing. Returns
    the path written, or None when the store is off. Never raises — a
    bench must not fail because archiving did."""
    try:
        if dirpath is None and not enabled():
            return None
        if fingerprint is None:
            fid, cfg = config_fingerprint()
        else:
            fid, cfg = fingerprint, (config or {})
        metric = metric or leg
        rec = {"schema": SCHEMA, "kind": "bench", "run": run or run_id(),
               "ts": time.time(), "host": _host(), "leg": leg,
               "metric": metric,
               "sig": "bench.%s|%s" % (metric, fid),
               "fingerprint": fid, "config": cfg,
               "value": value, "unit": unit}
        if extra:
            rec["extra"] = extra
        path = append(rec, dirpath=dirpath)
        if path is not None:
            prune(dirpath=dirpath)
        return path
    except Exception:
        return None


def prune(dirpath=None, keep_n=None):
    """Enforce the per-signature retention cap on this host's file:
    keep the newest ``keep_n`` (default MXNET_OBS_PROFILE_KEEP) records
    per signature, atomically rewriting only when something must go.
    The read AND the rewrite happen under ``_lock`` + the cross-process
    ``_file_lock`` — a frame appended concurrently (other thread or
    other process on this host) lands either before the read (and is
    kept) or after the replace (O_APPEND onto the new file), never in
    the window where the rewrite would discard it. Returns the number
    of records dropped."""
    d = dirpath or store_dir()
    if not d:
        return 0
    path = host_file(d)
    if not os.path.exists(path):
        return 0
    keep_n = keep_n or keep()
    tmp = path + ".tmp.%d" % os.getpid()
    try:
        with _lock, _file_lock(path):
            records, _ev = read_file(path)
            by_sig = {}
            for i, r in enumerate(records):
                by_sig.setdefault(r.get("sig", ""), []).append(i)
            drop = set()
            for idxs in by_sig.values():
                if len(idxs) > keep_n:
                    idxs.sort(key=lambda i: (records[i].get("ts", 0), i))
                    drop.update(idxs[:-keep_n])
            if not drop:
                return 0
            kept = [r for i, r in enumerate(records) if i not in drop]
            with open(tmp, "wb") as f:
                for r in kept:
                    f.write(frame(r))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return 0
    return len(drop)


# --------------------------------------------------------- readers ---

def merge_by_signature(records):
    """Group scope records into one timeline per signature:
    {sig: {"scope", "sig", "records" (ts-sorted), "runs" (ordered)}}.
    The read side that makes two consecutive runs of the same workload
    ONE merged timeline."""
    groups = {}
    for r in records:
        if r.get("kind") != "scope":
            continue
        g = groups.setdefault(r.get("sig", ""), {
            "scope": normalize_scope(r.get("scope", "")),
            "sig": r.get("sig", ""), "records": []})
        g["records"].append(r)
    for g in groups.values():
        g["records"].sort(key=lambda r: r.get("ts", 0))
        runs, seen = [], set()
        for r in g["records"]:
            run = r.get("run")
            if run not in seen:
                seen.add(run)
                runs.append(run)
        g["runs"] = runs
    return groups


def runs_in(records):
    """Distinct run ids ordered by first appearance (ts order)."""
    runs, seen = [], set()
    for r in sorted(records, key=lambda r: r.get("ts", 0)):
        run = r.get("run")
        if run is not None and run not in seen:
            seen.add(run)
            runs.append(run)
    return runs


def run_series(group, metric="p50_ms"):
    """Per-run series for one merged signature group: the newest record
    of each run -> [(run, ts, value)]. ``metric`` reads span stats
    first, then top-level fields (bench ``value``, ``flops``...)."""
    newest = {}
    for r in group["records"]:
        newest[r.get("run")] = r
    out = []
    for run in group["runs"]:
        r = newest[run]
        stats = r.get("stats") or {}
        val = stats.get(metric, r.get(metric))
        if val is None and metric == "p50_ms" and stats.get("count"):
            val = stats.get("total_ms", 0) / stats["count"]
        if val is not None:
            out.append((run, r.get("ts", 0), float(val)))
    return out


def reset():
    """Forget the cached run id + device fingerprint (tests)."""
    with _lock:
        _run[0] = None
        _device_doc[0] = None
