"""mxnet_tpu.observability — unified runtime telemetry.

One low-overhead, thread-safe core (ring-buffer span recorder + named
counters/gauges, ``core.py``) feeds three exporters (``export.py``):
chrome://tracing JSON (merged into ``profiler.dump()``), an MXNet-style
aggregate percentile table (``profiler.dumps(aggregate=True)``), and a
Prometheus textfile for scraping long runs. ``recompile.py`` watches
jax.monitoring compile events and flags silent retraces with the
argument signature that caused them.

Enable with ``MXNET_OBS=1`` (or ``mx.profiler.set_state('run')``).
With the knob unset every instrumentation site reduces to one guarded
branch — the hot paths (kvstore dispatch, trainer step, io.next) stay
within noise (<2%, benchmark/allreduce_overlap_bench.py).

Instrumented out of the box: Trainer/Module step phases (forward /
backward / allreduce / update), KVStore push/pull/pushpull_fused
(per-bucket bytes, dtype lane, dispatch counts, wall time), the io.py
iterators (batch latency, prefetch wait), and the CachedOp/Executor
jit boundaries (compile spans + retrace attribution).

Multi-process jobs get the distributed half (``dist.py``,
``watchdog.py``): rank-tagged events, rank-suffixed dumps merged into
one per-rank-lane trace on a barrier-aligned timebase
(``merge_traces`` / ``tools/obs_merge.py``), cross-rank step-phase
straggler detection (``MXNET_OBS_SKEW_EVERY`` /
``MXNET_OBS_STRAGGLER_FACTOR``), and a collective hang watchdog that
dumps a post-mortem after ``MXNET_OBS_COLLECTIVE_TIMEOUT`` seconds
instead of hanging silently.
"""

from . import chaos
from . import core
from . import dist
from . import export
from . import hlo
from . import attribution
from . import recompile
from . import watchdog
from .attribution import (ops_enabled, format_ops_table,
                          compare_summaries)
from .attribution import summary as ops_summary
from .core import (enabled, set_enabled, span, counter, gauge,
                   record_span, record_instant, records, counters,
                   dropped, reset)
from .dist import (merge_traces, detect_stragglers, skew_summary,
                   exchange_phase_stats)
from .export import (chrome_trace, dump_chrome_trace, aggregate,
                     aggregate_table, prometheus_text, write_prometheus)
from .recompile import get_detector, note_call, record_retrace
from .watchdog import get_watchdog

__all__ = ["chaos", "core", "dist", "export", "hlo", "attribution",
           "recompile",
           "watchdog", "ops_enabled", "format_ops_table",
           "compare_summaries", "ops_summary", "enabled",
           "set_enabled", "span", "counter", "gauge", "record_span",
           "record_instant", "records", "counters", "dropped", "reset",
           "chrome_trace", "dump_chrome_trace", "aggregate",
           "aggregate_table", "prometheus_text", "write_prometheus",
           "get_detector", "note_call", "record_retrace", "merge_traces",
           "detect_stragglers", "skew_summary", "exchange_phase_stats",
           "get_watchdog"]
