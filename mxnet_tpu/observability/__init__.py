"""mxnet_tpu.observability — unified runtime telemetry.

One low-overhead, thread-safe core (ring-buffer span recorder + named
counters/gauges, ``core.py``) feeds three exporters (``export.py``):
chrome://tracing JSON (merged into ``profiler.dump()``), an MXNet-style
aggregate percentile table (``profiler.dumps(aggregate=True)``), and a
Prometheus textfile for scraping long runs. ``recompile.py`` watches
jax.monitoring compile events and flags silent retraces with the
argument signature that caused them.

Enable with ``MXNET_OBS=1`` (or ``mx.profiler.set_state('run')``).
With the knob unset every instrumentation site reduces to one guarded
branch — the hot paths (kvstore dispatch, trainer step, io.next) stay
within noise (<2%, benchmark/allreduce_overlap_bench.py).

Instrumented out of the box: Trainer/Module step phases (forward /
backward / allreduce / update), KVStore push/pull/pushpull_fused
(per-bucket bytes, dtype lane, dispatch counts, wall time), the io.py
iterators (batch latency, prefetch wait), and the CachedOp/Executor
jit boundaries (compile spans + retrace attribution).
"""

from . import core
from . import export
from . import recompile
from .core import (enabled, set_enabled, span, counter, gauge,
                   record_span, record_instant, records, counters,
                   dropped, reset)
from .export import (chrome_trace, dump_chrome_trace, aggregate,
                     aggregate_table, prometheus_text, write_prometheus)
from .recompile import get_detector, note_call, record_retrace

__all__ = ["core", "export", "recompile", "enabled", "set_enabled",
           "span", "counter", "gauge", "record_span", "record_instant",
           "records", "counters", "dropped", "reset", "chrome_trace",
           "dump_chrome_trace", "aggregate", "aggregate_table",
           "prometheus_text", "write_prometheus", "get_detector",
           "note_call", "record_retrace"]
