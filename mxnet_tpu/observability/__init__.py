"""mxnet_tpu.observability — unified runtime telemetry.

One low-overhead, thread-safe core (ring-buffer span recorder + named
counters/gauges, ``core.py``) feeds three exporters (``export.py``):
chrome://tracing JSON (merged into ``profiler.dump()``), an MXNet-style
aggregate percentile table (``profiler.dumps(aggregate=True)``), and a
Prometheus textfile for scraping long runs. ``recompile.py`` watches
jax.monitoring compile events and flags silent retraces with the
argument signature that caused them.

Enable with ``MXNET_OBS=1`` (or ``mx.profiler.set_state('run')``).
With the knob unset every instrumentation site reduces to one guarded
branch — the hot paths (kvstore dispatch, trainer step, io.next) stay
within noise (<2%, benchmark/allreduce_overlap_bench.py).

Instrumented out of the box: Trainer/Module step phases (forward /
backward / allreduce / update), KVStore push/pull/pushpull_fused
(per-bucket bytes, dtype lane, dispatch counts, wall time), the io.py
iterators (batch latency, prefetch wait), and the CachedOp/Executor
jit boundaries (compile spans + retrace attribution).

Multi-process jobs get the distributed half (``dist.py``,
``watchdog.py``): rank-tagged events, rank-suffixed dumps merged into
one per-rank-lane trace on a barrier-aligned timebase
(``merge_traces`` / ``tools/obs_merge.py``), cross-rank step-phase
straggler detection (``MXNET_OBS_SKEW_EVERY`` /
``MXNET_OBS_STRAGGLER_FACTOR``), and a collective hang watchdog that
dumps a post-mortem after ``MXNET_OBS_COLLECTIVE_TIMEOUT`` seconds
instead of hanging silently.

Serving gets the request-level half (``histogram.py``, ``slo.py``,
``http.py``): bounded-memory log-bucketed latency histograms
(``serving.ttft_ms``/``itl_ms``/``e2e_ms``/``queue_ms``, bucket-wise
mergeable across ranks), per-request lifecycle spans + chrome-trace
flow chains threaded through the ContinuousBatcher, ``MXNET_OBS_SLO``
violation counters with a rolling ``serving.slo_attainment`` gauge,
and a ``MXNET_OBS_HTTP`` live ``/metrics`` + ``/healthz`` scrape
endpoint (docs/OBSERVABILITY.md "Serving observability").
"""

from . import chaos
from . import core
from . import dist
from . import integrity
from . import events
from . import export
from . import flight
from . import histogram
from . import hlo
from . import http
from . import sideband
from . import slo
from . import membudget
from . import attribution
from . import profile_store
from . import costmodel
from . import goodput
from . import recompile
from . import timeseries
from . import watchdog
from .attribution import (ops_enabled, format_ops_table,
                          compare_summaries)
from .attribution import summary as ops_summary
from .core import (enabled, set_enabled, span, counter, gauge,
                   record_span, record_instant, record_flow, records,
                   counters, dropped, reset)
from .core import histogram as get_histogram
from .histogram import Histogram
from .http import start as start_http_server
from .http import stop as stop_http_server
from .dist import (merge_traces, detect_stragglers, skew_summary,
                   exchange_phase_stats)
from .export import (chrome_trace, dump_chrome_trace, aggregate,
                     aggregate_table, prometheus_text, write_prometheus)
from .recompile import get_detector, note_call, record_retrace
from .events import event
from .flight import record_incident, note_exit
from .goodput import (compute_ledger, critical_path, elastic_downtime,
                      note_step_commit)
from .watchdog import get_watchdog

# chain the flight recorder's unhandled-exception hook when telemetry
# is on (one guarded branch — PR 2 contract — when MXNET_OBS is unset)
if core.enabled():
    flight.install()

__all__ = ["chaos", "core", "dist", "events", "export", "flight",
           "goodput", "compute_ledger", "critical_path",
           "elastic_downtime", "note_step_commit",
           "histogram", "hlo",
           "http", "sideband", "slo", "membudget", "attribution",
           "integrity", "recompile", "timeseries",
           "event", "record_incident", "note_exit",
           "watchdog", "ops_enabled", "format_ops_table",
           "compare_summaries", "ops_summary", "enabled",
           "set_enabled", "span", "counter", "gauge", "get_histogram",
           "Histogram", "record_span", "record_instant", "record_flow",
           "records", "counters", "dropped", "reset",
           "start_http_server", "stop_http_server",
           "chrome_trace", "dump_chrome_trace", "aggregate",
           "aggregate_table", "prometheus_text", "write_prometheus",
           "get_detector", "note_call", "record_retrace", "merge_traces",
           "detect_stragglers", "skew_summary", "exchange_phase_stats",
           "get_watchdog"]
