"""Silent-corruption defense: fingerprints, replay audit, quarantine.

The fault-tolerance stack (io retries, step guards, watchdog, elastic
shrink, overload shedding) handles faults that *announce* themselves.
The nastiest production failures don't: a flipped bit in a gradient
bucket, a replica whose "replicated" weights have drifted, a
checkpoint that is internally consistent but descends from a corrupted
step. The TensorFlow system paper (PAPERS.md) treats consistency
checking of replicated state as a first-class system concern — and the
cross-replica weight-update sharding layout (fusion.ShardSlot) means a
single corrupt rank silently poisons EVERY replica through the
all-reduce unless the corruption is caught around the collective.

Three detectors, one response:

* **In-graph fingerprints** — a cheap device-side digest per bucket
  lane: ``[sum, L2, bitcast-xor-hi, bitcast-xor-lo]`` as four float32s
  (the xor halves are < 2^16, so float32 carries them exactly). The
  sum/L2 catch magnitude damage, the folded xor catches ANY single-bit
  flip (including exponent bits that leave the sum plausible). Every
  ``MXNET_INTEGRITY_EVERY`` steps the ranks all-gather their per-lane
  *parameter* fingerprints over the skew-exchange transport
  (``dist._allgather_vec`` — a pure gather, so the bit patterns travel
  exactly) and vote: a rank outside the strict majority is replica
  drift, named with rank + bucket/lane + key evidence. Two-rank ties
  are indeterminate (voting needs >= 3 ranks to localize) and warn
  naming both ranks.
* **Replay audit** — cross-rank voting can't see compute SDC that
  corrupts *this* rank's contribution before the collective folds it
  into everyone. On a sampled cadence
  (``MXNET_INTEGRITY_REPLAY_EVERY``) the kvstore records each lane's
  packed-gradient digest plus a closure that re-packs the lane from
  the still-live source arrays; at the step boundary the pack is
  replayed and re-digested — a mismatch is rank-local corruption, no
  vote needed.
* **Checkpoint lineage** — manifests carry a parameter fingerprint
  (``tree_fingerprint``) and a parent-manifest digest;
  ``models/checkpoint.verify_lineage`` walks the chain and the load
  paths refuse a checkpoint whose recomputed fingerprint mismatches
  (falling back to the newest verified ancestor).

A rank judged corrupt (``MXNET_INTEGRITY_ACTION=quarantine``, the
default) writes its evidence to the elastic sideband
(``quarantine.g<g>.rank<r>.json``) and exits with taxonomy code 46.
Survivors run the normal elastic shrink — but skip the shard capture
when the dead rank is quarantined, because survivor state downstream
of a poisoned all-reduce must not become the resume point; resume then
restores from the last *verified* checkpoint. The supervisor
(``tools/elastic_launch.py``) reads the evidence, prints it, and puts
the host on a regrow cooldown list.

Off-path contract (PR 2): with ``MXNET_INTEGRITY`` unset, every hook
reduces to one guarded ``enabled()`` branch — dispatch count and step
numerics are bit-identical to the pre-integrity behavior (tested by
tests/test_integrity.py off-path identity).
"""

import os
import sys
import time
import zlib

import numpy as np

from . import core as _obs
from .. import _fastenv

__all__ = ["QUARANTINE_EXIT_CODE", "enabled", "every", "replay_every",
           "action", "digest", "digest_arrays", "combine",
           "fingerprint_hex", "tree_fingerprint", "param_fingerprints",
           "audit_armed", "note_lane", "run_replay_audit",
           "exchange_and_vote", "step_boundary", "quarantine", "stats"]

# supervisor-visible exit taxonomy (docs/ROBUSTNESS.md): 43 watchdog,
# 44 elastic shrink, 45 generation boundary, 46 integrity quarantine
QUARANTINE_EXIT_CODE = 46

DEFAULT_EVERY = 32

# always-on cheap counters (the kv.dispatch_stats pattern)
stats = {"votes": 0, "audits": 0, "detected": 0, "quarantines": 0}


# ------------------------------------------------------------ env knobs --

def enabled():
    """THE site guard: MXNET_INTEGRITY=1 arms every detector. One
    `_fastenv` read when off — the PR 2 cost budget."""
    v = _fastenv.get("MXNET_INTEGRITY")
    return v is not None and v not in ("", "0", "false", "False")


def every():
    """MXNET_INTEGRITY_EVERY: steps between cross-rank parameter
    fingerprint votes (default 32; 0 disables the vote)."""
    try:
        return int(_fastenv.get("MXNET_INTEGRITY_EVERY", DEFAULT_EVERY))
    except (TypeError, ValueError):
        return DEFAULT_EVERY


def replay_every():
    """MXNET_INTEGRITY_REPLAY_EVERY: steps between replay audits
    (default = the vote cadence; 0 disables the audit)."""
    v = _fastenv.get("MXNET_INTEGRITY_REPLAY_EVERY")
    if v is None or v == "":
        return every()
    try:
        return int(v)
    except (TypeError, ValueError):
        return every()


def action():
    """MXNET_INTEGRITY_ACTION: ``quarantine`` (default — evidence to
    the elastic sideband, exit 46) or ``warn`` (detect and report
    only)."""
    v = (_fastenv.get("MXNET_INTEGRITY_ACTION") or "quarantine").lower()
    return v if v in ("warn", "quarantine") else "quarantine"


# ---------------------------------------------------------- the digest --

_digest_cache = {}


def _xor_fold(flat):
    """Traced xor-fold of a flat array's raw bits down to one uint32.
    Order-independent (xor commutes), so the verdict is stable under
    any reduction order — and ANY single flipped bit changes it."""
    import jax
    import jax.numpy as jnp
    it = np.dtype(flat.dtype).itemsize
    if it == 1:
        u = jax.lax.bitcast_convert_type(flat, jnp.uint8).astype(
            jnp.uint32)
    elif it == 2:
        u = jax.lax.bitcast_convert_type(flat, jnp.uint16).astype(
            jnp.uint32)
    elif it == 8:
        u64 = jax.lax.bitcast_convert_type(flat, jnp.uint64)
        x = jax.lax.reduce(u64, np.uint64(0), jax.lax.bitwise_xor, (0,))
        return ((x >> np.uint64(32)).astype(jnp.uint32)
                ^ (x & np.uint64(0xFFFFFFFF)).astype(jnp.uint32))
    else:
        u = jax.lax.bitcast_convert_type(flat, jnp.uint32)
    return jax.lax.reduce(u, np.uint32(0), jax.lax.bitwise_xor, (0,))


def _digest_fn(shape, dtype):
    """One cached jitted digest per array signature — the fingerprint
    costs a handful of reductions fused into one dispatch."""
    key = (tuple(shape), str(np.dtype(dtype)))
    fn = _digest_cache.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def _digest(x):
            flat = jnp.ravel(x)
            f = flat.astype(jnp.float32)
            s = jnp.sum(f)
            l2 = jnp.sum(f * f)
            x32 = _xor_fold(flat)
            hi = (x32 >> np.uint32(16)).astype(jnp.float32)
            lo = (x32 & np.uint32(0xFFFF)).astype(jnp.float32)
            return jnp.stack([s, l2, hi, lo])

        fn = _digest_cache[key] = jax.jit(_digest)
    return fn


def digest(arr):
    """``[sum, L2, xor_hi, xor_lo]`` of one jax array as a host
    float32[4] — see the module docstring for why each component."""
    try:
        import jax
        if (isinstance(arr, jax.Array)
                and len(arr.sharding.device_set) > 1
                and arr.is_fully_addressable):
            # multi-device layouts hit backends whose SPMD partitioner
            # rejects the xor reduction (CPU); the digest is a property
            # of the VALUE, so gathering first changes nothing
            arr = np.asarray(arr)
    except (ImportError, AttributeError):
        pass
    out = _digest_fn(np.shape(arr), arr.dtype)(arr)
    return np.asarray(out, np.float32)


def combine(digests):
    """Fold per-array digests into one lane digest: sums add, xor
    halves xor — deterministic on the host, so equal inputs on two
    ranks always yield byte-equal lane fingerprints."""
    acc = np.zeros(4, np.float32)
    xh = xl = 0
    for d in digests:
        d = np.asarray(d, np.float32)
        acc[0] = np.float32(acc[0] + d[0])
        acc[1] = np.float32(acc[1] + d[1])
        xh ^= int(d[2])
        xl ^= int(d[3])
    acc[2] = np.float32(xh)
    acc[3] = np.float32(xl)
    return acc


def digest_arrays(arrays):
    """Combined digest of a list of jax arrays (a lane's per-worker
    packed flats, a parameter group)."""
    return combine([digest(a) for a in arrays])


def fingerprint_hex(vec):
    """Stable compact rendering of a digest vector for evidence
    records — the exact float32 bit patterns, hex-encoded."""
    return np.asarray(vec, "<f4").tobytes().hex()


def tree_fingerprint(flat):
    """Host-side fingerprint of a ``{name: array}`` tree: a crc32 fold
    over the sorted entries' names, dtypes, shapes, and exact bytes.
    One function everywhere a parameter identity is compared —
    checkpoint manifests (``param_fingerprint``), serving
    ``health_snapshot``, the router's mixed-fleet check — so the same
    weights always produce the same 8-hex-char id."""
    acc = 0
    for k in sorted(flat):
        arr = np.ascontiguousarray(np.asarray(flat[k]))
        c = zlib.crc32(arr.tobytes()) & 0xFFFFFFFF
        acc = zlib.crc32(
            ("%s:%s:%s:%08x" % (k, arr.dtype, arr.shape, c)).encode(),
            acc) & 0xFFFFFFFF
    return "%08x" % acc


def params_fingerprint(params):
    """Fingerprint of a raw parameter pytree, flattened exactly the way
    checkpoint manifests flatten it — so this id compares equal to the
    manifest's ``param_fingerprint`` for the same weights. Serving's
    ``weight_fingerprint`` and the hot-swap lineage gate both resolve
    through here."""
    from ..models import checkpoint as _ckpt
    flat = {}
    _ckpt._flatten(params, _ckpt._PARAMS, flat)
    return tree_fingerprint(flat)


# ------------------------------------------------ parameter lane plans --

_plan_cache = {}


def _param_plan(items):
    """The PR 1 bucket plan over the parameter list (same priority
    order the trainer fuses gradients in), cached by plan signature —
    vote evidence names the same bucket/lane a corrupt gradient would
    ride."""
    from ..parallel import fusion
    entries = [(k, tuple(np.shape(a)), str(np.dtype(a.dtype)))
               for k, a in items]
    sig = fusion.plan_signature(entries)
    plan = _plan_cache.get(sig)
    if plan is None:
        plan = _plan_cache[sig] = fusion.plan_buckets(entries)
    return plan


def param_fingerprints(items):
    """Per-bucket-lane parameter fingerprints. ``items``: ordered
    ``(key, jax array)`` pairs. Returns ``(vec, lanes)`` — ``vec`` a
    float32[4 * n_lanes] ready for the all-gather, ``lanes`` the
    matching ``(bucket_index, dtype, keys)`` evidence labels."""
    plan = _param_plan(items)
    data = dict(items)
    vecs, lanes = [], []
    for bucket in plan:
        for lane in bucket.lanes:
            vecs.append(digest_arrays(
                [data[seg.key] for seg in lane.segments]))
            lanes.append((bucket.index, lane.dtype,
                          [seg.key for seg in lane.segments]))
    if not vecs:
        return np.zeros(0, np.float32), []
    return np.concatenate(vecs).astype(np.float32), lanes


# ------------------------------------------------------- replay audit --

_state = {"steps": 0}
_pending = []            # lanes recorded for this step's replay audit
_PENDING_CAP = 512       # safety for kvstore use outside a step loop


def audit_armed():
    """Whether THIS step's fused lanes should be recorded for replay
    (decided before the step boundary increments the counter)."""
    n = replay_every()
    return n > 0 and _state["steps"] % n == 0


def note_lane(bucket_index, lane_dtype, per_worker, repack):
    """kvstore hook (caller guards ``enabled()``): when the audit is
    armed, digest the packed flats that are about to feed the
    collective and keep ``repack`` — a closure re-packing the lane
    from the still-live source arrays (jax arrays are immutable, so
    the sources can't be mutated out from under us)."""
    if not audit_armed():
        return
    if len(_pending) >= _PENDING_CAP:
        del _pending[0]
    _pending.append({"bucket": int(bucket_index),
                     "lane": str(lane_dtype),
                     "digest": digest_arrays(per_worker),
                     "repack": repack})


def run_replay_audit():
    """Re-pack every recorded lane and compare digests bitwise (NaN
    payloads compare by bits, not by float equality). Returns the
    evidence list for mismatching lanes — rank-LOCAL corruption: the
    recorded flats this rank fed the collective do not match what its
    own inputs produce."""
    bad = []
    if not _pending:
        return bad
    stats["audits"] += 1
    if _obs.enabled():
        _obs.counter("integrity.audits").add(1)
    for rec in _pending:
        clean = digest_arrays(rec["repack"]())
        if clean.tobytes() != rec["digest"].tobytes():
            bad.append({"kind": "replay_mismatch",
                        "bucket": rec["bucket"], "lane": rec["lane"],
                        "recorded": fingerprint_hex(rec["digest"]),
                        "recomputed": fingerprint_hex(clean)})
    del _pending[:]
    return bad


# ------------------------------------------------------- the vote --

def exchange_and_vote(items, allgather=None, rank=None):
    """One cross-rank parameter vote: all-gather the per-lane
    fingerprints (``dist._allgather_vec`` — a pure gather, bit-exact
    transport) and group ranks by exact fingerprint bytes per lane.

    Returns ``{"drift": [...], "indeterminate": [...]}``: a strict
    majority flags the minority ranks as replica drift with named
    rank + bucket/lane + key evidence; a tie (2-rank split) is
    indeterminate — voting needs >= 3 ranks to localize — and names
    every disagreeing rank instead."""
    from . import dist as _dist
    vec, lanes = param_fingerprints(items)
    gathered = np.asarray(
        (_dist._allgather_vec if allgather is None else allgather)(vec),
        np.float32)
    rank = _dist.process_index() if rank is None else int(rank)
    world = gathered.shape[0]
    stats["votes"] += 1
    if _obs.enabled():
        _obs.counter("integrity.votes").add(1)
    drift, indeterminate = [], []
    for li, (bidx, dtype, keys) in enumerate(lanes):
        rows = gathered[:, 4 * li:4 * li + 4]
        groups = {}
        for r in range(world):
            groups.setdefault(rows[r].tobytes(), []).append(r)
        if len(groups) == 1:
            continue
        maj = max(groups.values(), key=len)
        ev = {"bucket": int(bidx), "lane": str(dtype), "keys": keys,
              "step": _state["steps"], "rank": rank,
              "fingerprints": {str(rs[0]): rows[rs[0]].tobytes().hex()
                               for rs in groups.values()}}
        if 2 * len(maj) > world:
            minority = sorted(r for g in groups.values()
                              if g is not maj for r in g)
            drift.append(dict(ev, kind="replica_drift",
                              drifted=minority,
                              majority=fingerprint_hex(rows[maj[0]])))
        else:
            indeterminate.append(dict(
                ev, kind="drift_indeterminate",
                disagreeing=sorted(r for g in groups.values()
                                   for r in g)))
    return {"drift": drift, "indeterminate": indeterminate}


# ------------------------------------------------- verdict + quarantine --

def quarantine(evidence, exit=None):
    """The corrupt-rank exit: write the evidence record to the elastic
    sideband (survivors and the supervisor both read it), count,
    flush, and leave with taxonomy code 46. ``exit`` is injectable for
    tests; the default is ``os._exit`` — a rank judged corrupt must
    not run cleanup that touches shared state."""
    import socket
    from ..parallel import elastic
    rank = elastic.rank_env()
    gen = elastic.generation_env()
    rec = {"rank": rank, "generation": gen,
           "host": "%s:rank%d" % (socket.gethostname(), rank),
           "wall": time.time(), "evidence": evidence}
    stats["quarantines"] += 1
    d = elastic.elastic_dir()
    if d:
        try:
            elastic.write_quarantine_record(d, rank, gen, rec)
        except OSError:
            pass
    if _obs.enabled():
        _obs.counter("integrity.quarantine").add(1)
        _obs.record_instant("integrity.quarantine", cat="integrity",
                            args={"rank": rank, "generation": gen,
                                  "kind": evidence.get("kind")})
    print("[integrity] rank %d g%d: QUARANTINE — %s" % (rank, gen,
                                                        evidence),
          file=sys.stderr, flush=True)
    sys.stdout.flush()
    from . import flight as _flight
    _flight.record_incident(
        "integrity.quarantine", exit_code=QUARANTINE_EXIT_CODE,
        quarantine_rank=rank, generation=gen, evidence=evidence)
    if exit is not None:
        exit(QUARANTINE_EXIT_CODE)
        return
    os._exit(QUARANTINE_EXIT_CODE)      # pragma: no cover - fatal


def _detected(evidence, exit=None):
    """One corruption verdict against THIS rank: count, report, and
    either quarantine or (action=warn) keep running."""
    stats["detected"] += 1
    if _obs.enabled():
        _obs.counter("integrity.detected").add(1)
        _obs.record_instant("integrity.detected", cat="integrity",
                            args=evidence)
    if action() == "quarantine":
        quarantine(evidence, exit=exit)
    else:
        print("[integrity] corruption detected (action=warn): %s"
              % (evidence,), file=sys.stderr, flush=True)


def _report(evidence):
    """A verdict about ANOTHER rank (or indeterminate): evidence goes
    to the trace and stderr; only the corrupt rank removes itself."""
    stats["detected"] += 1
    if _obs.enabled():
        _obs.counter("integrity.detected").add(1)
        _obs.record_instant("integrity.detected", cat="integrity",
                            args=evidence)
    print("[integrity] %s" % (evidence,), file=sys.stderr, flush=True)


# ------------------------------------------------------ the step hook --

def step_boundary(items=None, kv=None, allgather=None, rank=None,
                  world=None, exit=None):
    """Trainer/Module per-step hook (callers guard ``enabled()``).

    Runs the replay audit over lanes recorded during this step's fused
    all-reduce, then — every ``MXNET_INTEGRITY_EVERY`` steps of a
    multi-worker job — the cross-rank parameter vote. The vote is a
    collective (every rank reaches it at the same deterministic step
    count, the ``dist.step_boundary`` skew-exchange pattern).
    ``items``: ordered ``(key, jax array)`` parameter pairs.
    ``allgather``/``rank``/``world``/``exit`` are injectable for
    tests."""
    if not enabled():
        return
    for ev in run_replay_audit():
        _detected(ev, exit=exit)
    n = every()
    if n > 0 and items and _state["steps"] % n == 0:
        if world is None:
            if kv is not None:
                world = getattr(kv, "num_workers", 1)
            else:
                from . import dist as _dist
                world = _dist.process_count()
        if world > 1 or allgather is not None:
            from . import dist as _dist
            rank = _dist.process_index() if rank is None else int(rank)
            verdicts = exchange_and_vote(items, allgather=allgather,
                                         rank=rank)
            for ev in verdicts["indeterminate"]:
                _report(ev)
            for ev in verdicts["drift"]:
                if rank in ev["drifted"]:
                    _detected(ev, exit=exit)
                else:
                    _report(ev)
    _state["steps"] += 1


def _reset_for_tests():
    """Clear counters, pending lanes, and caches."""
    _state["steps"] = 0
    del _pending[:]
    _plan_cache.clear()
    for k in stats:
        stats[k] = 0
