"""Optimized-HLO parsing and cost attribution primitives.

Promoted out of ``benchmark/hlo_diff.py`` (which is now a thin wrapper
over this module) so per-instruction cost accounting has exactly ONE
implementation: the observability attribution layer, the benchmarks and
the regression sentinel all read the same numbers.

What lives here:

* ``parse_hlo(text)`` — the optimized-HLO text of a compiled executable
  (``compiled.as_text()``) as a list of per-instruction rows carrying
  output bytes, estimated HBM bytes accessed, estimated flops, the
  ``op_name`` metadata XLA preserved from the jaxpr, and the owning
  computation (entry vs fused).
* ``scope_of(op_name, known)`` — map an instruction's ``op_name`` path
  back to the originating named scope (the Gluon block prefix / symbol
  node name that ``jax.named_scope`` stamped at trace time), unwrapping
  the transform decorations jax adds (``jvp(...)``,
  ``transpose(jvp(...))``, ``remat(...)``, ...).
* ``group_by_scope(rows, known)`` — per-scope flops / HBM bytes /
  output bytes / instruction counts, plus totals.
* ``peak_watermark(rows)`` — a def-to-last-use liveness sweep over the
  entry computation: the peak live-byte watermark and, at the peak
  instant, the live bytes attributed per scope.
* ``normalize_cost_analysis(ca)`` / ``compiled_cost(compiled)`` — the
  ``ca[0] if isinstance(ca, (list, tuple))`` dance that was copy-pasted
  across three benchmarks, in one place.

Accounting model (same as hlo_diff always used): HBM bytes accessed of
a top-level (entry) instruction = its output bytes + the output bytes
of its operands — "bytes accessed" minus fusion-internal elision, which
is exactly what fusion boundaries make true on the device. Instructions
inside fused computations therefore contribute flops but no HBM bytes;
the enclosing fusion instruction carries the traffic. Flops are
shape-derived estimates (2*M*N*K matmuls, 2*out*kernel convs, one per
output element for elementwise/reduce lanes) — deterministic, platform
independent, and precise enough to rank scopes and to diff runs; use
``compiled_cost`` when you want XLA's own totals next to them.
"""

import re
from collections import defaultdict

__all__ = ["DTYPE_BYTES", "shape_bytes", "parse_hlo", "scope_of",
           "attribute_rows", "group_by_scope", "peak_watermark",
           "normalize_cost_analysis", "compiled_cost",
           "instruction_flops", "SKIP_OPCODES"]

DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1, "s32": 4, "u32": 4,
    "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%?[\w.-]+) = (\([^)]*\)|\S+) ([\w-]+)\((.*)$")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')
_COMPUTATION_RE = re.compile(r"^(ENTRY\s+)?(%?[\w.-]+)\s*(?:\(.*)?\{\s*$")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_CALLS_RE = re.compile(r"calls=%?([\w.-]+)")

# wrappers jax's name stack adds around user named_scope components
_TRANSFORMS = frozenset([
    "jit", "pjit", "jvp", "vjp", "transpose", "vmap", "pmap", "remat",
    "checkpoint", "custom_jvp", "custom_vjp", "while", "body", "cond",
    "scan", "shard_map", "named", "rematted_computation",
])

# data movement / bookkeeping: no flops, and no HBM accounting of their
# own (parameters and constants are charged to their consumers)
SKIP_OPCODES = ("parameter", "constant", "tuple", "get-tuple-element",
                "bitcast")

# one flop per output element
_ELEMENTWISE = frozenset([
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "power", "remainder", "atan2", "exponential", "exponential-minus-one",
    "log", "log-plus-one", "logistic", "tanh", "sqrt", "rsqrt", "cbrt",
    "sine", "cosine", "tan", "negate", "abs", "sign", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "compare", "select",
    "clamp", "and", "or", "xor", "not", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "convert",
    "is-finite", "rng", "rng-bit-generator", "map", "iota",
])


def shape_bytes(spec):
    """Total bytes of an HLO shape spec (handles tuples)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(spec):
        if dtype not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES[dtype]
    return total


def _shape_dims(spec):
    """(elements, dims-of-first-array) of a shape spec; tuples report
    the element count of the first component (enough for ranking)."""
    m = _SHAPE_RE.search(spec)
    if not m:
        return 0, ()
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    n = 1
    for d in dims:
        n *= d
    return n, dims


def instruction_flops(opcode, out_elems, rest, operands):
    """Shape-derived flop estimate for one parsed instruction.

    ``operands`` is the list of resolved operand rows (dicts with
    ``elems``/``dims``) in reference order; missing operands degrade
    gracefully to coarser estimates.
    """
    if opcode == "dot":
        contract = 1
        m = _LHS_CONTRACT_RE.search(rest)
        lhs = operands[0] if operands else None
        if m and lhs is not None and lhs.get("dims"):
            for d in m.group(1).split(","):
                if d and int(d) < len(lhs["dims"]):
                    contract *= lhs["dims"][int(d)]
        return 2.0 * out_elems * contract
    if opcode == "convolution":
        kern = operands[1] if len(operands) > 1 else None
        if kern is not None and kern.get("elems"):
            out_ch = 1
            m = _DIM_LABELS_RE.search(rest)
            if m and "o" in m.group(2) and kern.get("dims"):
                pos = m.group(2).index("o")
                if pos < len(kern["dims"]):
                    out_ch = max(kern["dims"][pos], 1)
            return 2.0 * out_elems * kern["elems"] / out_ch
        return 2.0 * out_elems
    if opcode in ("reduce", "reduce-window"):
        src = operands[0] if operands else None
        return float(src["elems"]) if src and src.get("elems") \
            else float(out_elems)
    if opcode in _ELEMENTWISE:
        return float(out_elems)
    return 0.0


def parse_hlo(text):
    """Parse optimized-HLO text into per-instruction rows.

    Returns a list of dicts: ``name``, ``opcode``, ``computation``,
    ``entry`` (bool), ``out`` (output bytes), ``elems``, ``dims``,
    ``operands`` (names), ``accessed`` (HBM byte estimate; 0 for
    instructions inside non-entry computations), ``flops``,
    ``op_name``. Rows appear in program order per computation,
    computations in file order.
    """
    rows = []
    comp = ""
    entry = False
    local = {}          # name -> row, per computation
    per_comp = {}       # computation -> {name: row}
    for line in text.splitlines():
        if not line.startswith(" ") and line.rstrip().endswith("{"):
            m = _COMPUTATION_RE.match(line.strip())
            if m:
                comp = m.group(2).lstrip("%")
                entry = bool(m.group(1)) or "ENTRY" in line.split("{")[0]
                local = per_comp.setdefault(comp, {})
                continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape, opcode, rest = m.groups()
        name = name.lstrip("%")
        out = shape_bytes(shape)
        elems, dims = _shape_dims(shape)
        # operand refs live before the closing paren of the arg list
        depth = 1
        arglist = []
        for ch in rest:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    break
            arglist.append(ch)
        ops = [ref for ref in re.findall(r"%?([\w.-]+)", "".join(arglist))
               if ref in local]
        meta = _METADATA_RE.search(rest)
        calls = _CALLS_RE.search(rest) if opcode == "fusion" else None
        row = {
            "name": name, "opcode": opcode, "computation": comp,
            "entry": entry, "out": out, "elems": elems, "dims": dims,
            "operands": ops,
            "calls": calls.group(1) if calls else None,
            "op_name": meta.group(1) if meta else "",
        }
        row["flops"] = 0.0 if opcode in SKIP_OPCODES else \
            instruction_flops(opcode, elems,
                              rest, [local[o] for o in ops])
        local[name] = row
        rows.append(row)
    for row in rows:
        if row["entry"] and row["opcode"] not in SKIP_OPCODES:
            local = per_comp.get(row["computation"], {})
            row["accessed"] = row["out"] + sum(
                local[o]["out"] for o in row["operands"] if o in local)
        else:
            row["accessed"] = 0
    return rows


def _unwrap(component):
    """Strip nested transform wrappers: 'transpose(jvp(scope))' ->
    'scope'; 'jit(relu)' -> 'relu'. Returns the innermost token."""
    token = component
    while True:
        i = token.find("(")
        if i <= 0 or not token.endswith(")"):
            return token
        head = token[:i]
        if head not in _TRANSFORMS:
            return token
        token = token[i + 1:-1]


def scope_of(op_name, known=None):
    """The named scope an instruction's ``op_name`` path belongs to.

    With ``known`` (a set of scope names the runtime registered at
    trace time) the RIGHTMOST path component that unwraps to a known
    scope wins — the finest enclosing block. Without ``known`` a
    heuristic keeps any unwrapped component that is not a transform
    and not the final (primitive) component.
    """
    if not op_name:
        return None
    parts = op_name.split("/")
    best = None
    for i, part in enumerate(parts):
        token = _unwrap(part)
        if not token or token in _TRANSFORMS:
            continue
        if known is not None:
            if token in known:
                best = token
        elif i < len(parts) - 1 and "(" not in token:
            best = token
    return best


def attribute_rows(rows, known=None):
    """Annotate every row with its source ``scope`` (None when truly
    unattributable). Three passes:

    1. the row's own ``op_name`` metadata (``scope_of``);
    2. ``fusion`` instructions whose metadata names no scope inherit
       the DOMINANT scope of their fused computation (weighted by
       flops, then output bytes) — XLA occasionally drops the fusion
       root's metadata while the fused instructions keep theirs;
    3. metadata-less data movement (layout copies/transposes XLA
       inserts with no op_name) inherits its first attributed
       operand's scope — the traffic exists to feed that scope.
    """
    comps = {}
    for r in rows:
        comps.setdefault(r["computation"], {})[r["name"]] = r
    for r in rows:
        r["scope"] = scope_of(r["op_name"], known)
    for r in rows:
        if r["scope"] is None and r.get("calls"):
            weights = {}
            for ir in comps.get(r["calls"], {}).values():
                s = ir["scope"]
                if s:
                    weights[s] = weights.get(s, 0.0) + max(
                        ir["flops"], float(ir["out"]), 1.0)
            if weights:
                r["scope"] = max(weights.items(),
                                 key=lambda kv: kv[1])[0]
    for _ in range(2):          # chains: copy-of-copy resolves pass 2
        unresolved = False
        for r in rows:
            if r["scope"] is not None \
                    or r["opcode"] in ("parameter", "constant"):
                continue
            local = comps[r["computation"]]
            for o in r["operands"]:
                src = local.get(o)
                if src is not None and src["scope"]:
                    r["scope"] = src["scope"]
                    break
            unresolved = unresolved or r["scope"] is None
        if not unresolved:
            break
    return rows


def group_by_scope(rows, known=None, unattributed="(unattributed)"):
    """Aggregate parsed rows per source scope (rows are run through
    ``attribute_rows`` unless already annotated).

    Returns ``(scopes, totals)`` where ``scopes`` maps scope name ->
    {count, flops, hbm_bytes, out_bytes} and ``totals`` carries the
    same fields plus ``attributed_flops`` / ``attributed_hbm_bytes``
    (everything not under the ``unattributed`` key).
    """
    if rows and "scope" not in rows[0]:
        attribute_rows(rows, known)
    scopes = defaultdict(lambda: {"count": 0, "flops": 0.0,
                                  "hbm_bytes": 0, "out_bytes": 0})
    totals = {"count": 0, "flops": 0.0, "hbm_bytes": 0, "out_bytes": 0,
              "attributed_flops": 0.0, "attributed_hbm_bytes": 0}
    for row in rows:
        if row["opcode"] in SKIP_OPCODES:
            continue
        scope = row["scope"] or unattributed
        ent = scopes[scope]
        ent["count"] += 1
        ent["flops"] += row["flops"]
        ent["hbm_bytes"] += row["accessed"]
        if row["entry"]:
            ent["out_bytes"] += row["out"]
            totals["out_bytes"] += row["out"]
        totals["count"] += 1
        totals["flops"] += row["flops"]
        totals["hbm_bytes"] += row["accessed"]
        if scope != unattributed:
            totals["attributed_flops"] += row["flops"]
            totals["attributed_hbm_bytes"] += row["accessed"]
    return dict(scopes), totals


def peak_watermark(rows, known=None, unattributed="(unattributed)"):
    """Liveness sweep over the ENTRY computation: each buffer lives
    from its defining instruction to its last top-level use (the root
    stays live to the end). Returns ``(peak_bytes, by_scope)`` where
    ``by_scope`` attributes the bytes live at the peak instant to the
    scope of each buffer's producer (parameters land under
    ``(parameters)``).
    """
    if rows and "scope" not in rows[0]:
        attribute_rows(rows, known)
    entry = [r for r in rows if r["entry"]]
    if not entry:
        return 0, {}
    index = {r["name"]: i for i, r in enumerate(entry)}
    last_use = {}
    for i, r in enumerate(entry):
        for op in r["operands"]:
            if op in index:
                last_use[op] = i
    n = len(entry)
    for r in entry:
        # outputs (and anything never consumed at top level) stay live
        last_use.setdefault(r["name"], n - 1)
    births = defaultdict(list)
    deaths = defaultdict(list)
    for r in entry:
        if r["opcode"] in ("tuple", "get-tuple-element", "bitcast"):
            continue    # aliases, not allocations
        i = 0 if r["opcode"] == "parameter" else index[r["name"]]
        births[i].append(r)
        deaths[last_use[r["name"]]].append(r)
    live = 0
    live_set = set()
    peak = 0
    peak_set = ()
    for i in range(n):
        for r in births.get(i, ()):
            live += r["out"]
            live_set.add(r["name"])
        if live > peak:
            peak = live
            peak_set = tuple(live_set)
        for r in deaths.get(i, ()):
            live -= r["out"]
            live_set.discard(r["name"])
    by_name = {r["name"]: r for r in entry}
    by_scope = defaultdict(int)
    for name in peak_set:
        r = by_name[name]
        if r["opcode"] == "parameter":
            by_scope["(parameters)"] += r["out"]
        else:
            by_scope[r["scope"] or unattributed] += r["out"]
    return peak, dict(by_scope)


# ------------------------------------------------- cost_analysis glue --

def normalize_cost_analysis(ca):
    """XLA's ``compiled.cost_analysis()`` has returned a dict, a list of
    dicts (one per partition), or None across jax versions. Normalize to
    ONE plain dict ({} when unavailable) — the helper the benchmarks
    used to each reimplement inline."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else {}


def compiled_cost(compiled):
    """``normalize_cost_analysis`` over a compiled executable, tolerating
    backends that raise instead of returning None."""
    try:
        return normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        return {}
