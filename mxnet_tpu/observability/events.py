"""Structured scheduler decision log — the narration layer.

The serving stack *counts* every decision it makes (``router.shed``,
``serving.preemptions``, ``serving.brownout_rung`` ...) but never
*narrates* them: by the time an operator looks, the counter says "7
preemptions" with no victims, no order, no context. ``event(kind,
**fields)`` is the one-call fix — a bounded ring of structured records
(``MXNET_OBS_EVENTS_RING`` entries, default 1024, oldest overwritten)
capturing WHO and WHY at each decision point:

    admit / shed / expire        admission control verdicts
    preempt                      victim rid + blocks freed
    brownout                     rung transitions (from -> to)
    breaker                      replica breaker state changes
    spec_k                       per-lane speculative-k adaptation
    pool_shrink / pool_grow      elastic KV-pool resizes
    swap / rollback              weight rollout lifecycle
    elastic                      generation changes (world N -> N')
    anomaly                      trend-detector firings (timeseries)

Every event is mirrored into the core ring as a chrome instant
(``event.<kind>``, cat ``decision``) so traces carry the narration on
the same timeline as the spans, and ``format_recent()`` renders the
"Recent events" section of ``profiler.dumps(aggregate=True)``. The
flight recorder snapshots ``recent()`` into every incident bundle.

PR 2 contract: with ``MXNET_OBS`` unset, ``event()`` is one guarded
branch — no ring, no clock read, no dict building at call sites that
pass only scalars.
"""

import threading

from . import core
from .. import _fastenv

__all__ = ["DEFAULT_RING", "event", "recent", "depth", "counts",
           "dropped", "ring_capacity", "format_recent", "reset"]

DEFAULT_RING = 1024

_lock = threading.Lock()
_ring = []
_head = 0
_total = 0
_counts = {}


def ring_capacity():
    return max(int(_fastenv.get("MXNET_OBS_EVENTS_RING", DEFAULT_RING)),
               1)


def event(kind, **fields):
    """Record one scheduler decision. No-op when telemetry is off;
    mirrored as a chrome instant ``event.<kind>`` when on."""
    global _head, _total
    if not core.enabled():
        return
    t_us = core._now_us()
    rec = (t_us, str(kind), fields)
    with _lock:
        if not _ring:
            _ring.extend([None] * ring_capacity())
        ring = _ring
        ring[_head] = rec
        _head = (_head + 1) % len(ring)
        _total += 1
        _counts[kind] = _counts.get(kind, 0) + 1
    core.record_instant("event." + str(kind), cat="decision",
                        args=fields)


def recent(n=None):
    """The last ``n`` events (all retained when None), oldest first:
    list of ``(t_us, kind, fields)``."""
    with _lock:
        if not _ring:
            return []
        if _total <= len(_ring):
            out = [r for r in _ring[:_head] if r is not None]
        else:
            out = [r for r in _ring[_head:] + _ring[:_head]
                   if r is not None]
    return out if n is None else out[-n:]


def depth():
    """Events currently held in the ring (the /healthz number)."""
    with _lock:
        return min(_total, len(_ring)) if _ring else 0


def counts():
    """Lifetime per-kind event counts (survive ring overwrite)."""
    with _lock:
        return dict(_counts)


def dropped():
    with _lock:
        return max(_total - len(_ring), 0) if _ring else 0


def format_recent(k=20):
    """The "Recent events" aggregate-table section: the last ``k``
    decisions, one line each, plus the per-kind lifetime tallies."""
    evs = recent(k)
    if not evs:
        return []
    lines = ["", "Recent events (last %d of %d, %d dropped):"
             % (len(evs), _total, dropped())]
    for t_us, kind, fields in evs:
        kv = " ".join("%s=%s" % (key, fields[key])
                      for key in sorted(fields))
        lines.append("  %12.3f ms  %-12s %s"
                     % (t_us / 1000.0, kind, kv))
    tally = counts()
    lines.append("  by kind: " + ", ".join(
        "%s=%d" % (key, tally[key]) for key in sorted(tally)))
    return lines


def reset():
    """Clear the ring and tallies (tests, new profile sessions)."""
    global _ring, _head, _total
    with _lock:
        _ring = []
        _head = 0
        _total = 0
        _counts.clear()
