"""Goodput ledger — whole-run wall-clock accounting + badput taxonomy
+ cross-rank critical-path attribution.

Every other observability layer answers "how long did X take"; this one
answers the question the north star actually asks: **of every
wall-clock second the run consumed, how many produced committed train
steps or emitted-and-kept serving tokens, and where did the rest go?**
The TF system paper and the cross-replica sharding work (PAPERS.md)
both treat whole-fleet utilization accounting — not per-op timing — as
the operative metric at scale; five robustness PRs (elastic shrink,
preemption+resume, OOM re-lowering, journal replay, brownout) added
recovery paths whose *cost in lost time* lands here.

The ledger classifies 100% of the observed wall window into:

* **goodput** — time under committed work spans: the train-step lattice
  (``trainer.step``/``forward``/``backward``/``allreduce``/``update``,
  minus guard-skipped and OOM-failed steps) and the serving compute
  spans (``serving.dispatch``/``sync``/``prefill``/``patch``).
* a **badput taxonomy** (``CATEGORIES``): ``data_stall`` (io.next /
  io.prefetch_wait), ``recompile`` (PR 2 detector instants, whose
  ``duration_s`` reconstructs the compile interval), ``checkpoint``
  (save/snapshot spans), ``guard_skipped`` (step spans containing a
  ``chaos.step_skipped`` marker), ``oom_relower`` (step spans
  containing a ``mem.oom`` marker), ``elastic_recovery``
  (``elastic.recovered`` instants in-run; the cross-generation
  stitching below for whole-timeline downtime), ``preempt_stall``
  (serving.preempt -> serving.resumed, FIFO-paired),
  ``requeue_redone`` (the re-prefill a requeued request pays),
  ``spec_rejected`` (dispatch time times the rejected-draft fraction),
  and ``brownout`` (non-goodput gaps while the brownout rung is up).
* an **untracked** remainder the ledger is *required* to keep small
  (``MXNET_OBS_GOODPUT_WARN``, default 5%).

Categories overlap in time (a recompile fires inside a step span); the
sweep resolves every elementary segment to the highest-priority
covering category (``_PRIORITY``), so the invariant

    goodput + sum(badput) + untracked == wall

holds exactly by construction. ``brownout`` ranks BELOW goodput:
throttled-but-working time is goodput, only the throttle's idle gaps
are badput.

**Elastic downtime across generations**: a process that died at
generation g cannot time its own absence. ``elastic_downtime`` stitches
the ``MXNET_ELASTIC_DIR`` sideband into one timeline: the
``shrink.g<g>.json`` wall stamp (failure detected) to the
``goodput.firstcommit.g<g>.rank<r>.json`` record the first committed
step of g writes (``note_step_commit``), so the recovery interval spans
the generation boundary by construction.

**Critical path** (``critical_path``): over a PR 3 merged trace, the
i-th ``trainer.step`` span of every rank lane is one step on the common
timebase; the step's wall time runs from the earliest rank's phase
start to the latest rank's step end, the rank that ends last is the
*critical rank*, and its forward/backward/allreduce/update durations —
plus the skew it started late by — bound the step. Aggregated: "step
time is X% bound by rank r backward, Y% by allreduce, Z% by straggler
skew".

Surfaces: ``goodput.fraction`` / ``badput.<category>_ms`` gauges
(all three PR 2 exporters), an aggregate-table section, fresh
``mxnet_obs_goodput_*`` Prometheus series, the ``/healthz`` ``goodput``
key, PR 17 incident bundles, and per-run ``goodput.*`` scope records in
the PR 18 profile store so ``perf_timeline`` / ``obs_regression
--history`` trend goodput across runs like any scope timing.

Off path (``MXNET_OBS`` unset) everything here is one guarded branch
with zero new I/O; ``MXNET_OBS_GOODPUT=0`` disables the ledger alone.
"""

import json
import os
import re
import time

from . import core
from .. import _fastenv

__all__ = ["CATEGORIES", "enabled", "warn_fraction",
           "events_from_ring", "events_from_trace", "compute_ledger",
           "critical_path", "format_table", "format_table_section",
           "prometheus_lines", "healthz_snapshot", "publish",
           "archive_run", "on_dump", "note_step_commit",
           "first_commit_path", "read_first_commit",
           "elastic_downtime", "reset"]

# badput taxonomy, in report order
CATEGORIES = ("data_stall", "recompile", "checkpoint", "guard_skipped",
              "oom_relower", "elastic_recovery", "preempt_stall",
              "requeue_redone", "spec_rejected", "brownout")

# sweep priority, highest first: a segment covered by several
# categories is charged to the first one here. brownout sits BELOW
# goodput on purpose (throttled-but-working time is goodput; only the
# throttle's idle gaps are badput).
_PRIORITY = ("elastic_recovery", "recompile", "checkpoint",
             "guard_skipped", "oom_relower", "data_stall",
             "preempt_stall", "requeue_redone", "goodput", "brownout")

# spans whose time is committed work (step spans filtered by the
# skip/oom markers before entering this union)
_GOODPUT_SPANS = frozenset((
    "trainer.step", "forward", "backward", "allreduce", "update",
    "serving.dispatch", "serving.sync", "serving.prefill",
    "serving.patch"))
_SERVING_DISPATCH = frozenset(("serving.dispatch", "serving.sync"))
_STEP_SPANS = frozenset(("trainer.step", "update"))
_STALL_SPANS = frozenset(("io.next", "io.prefetch_wait"))


def enabled():
    """THE off-path guard: telemetry on AND MXNET_OBS_GOODPUT not
    explicitly disabled (default on — the ledger reads the ring that
    already exists, costing nothing extra per step)."""
    if not core.enabled():
        return False
    v = _fastenv.get("MXNET_OBS_GOODPUT")
    return v not in ("0", "false", "False")


def warn_fraction():
    """MXNET_OBS_GOODPUT_WARN: the untracked fraction above which the
    table flags the ledger itself as incomplete (default 0.05)."""
    try:
        return float(_fastenv.get("MXNET_OBS_GOODPUT_WARN", 0.05))
    except (TypeError, ValueError):
        return 0.05


# ------------------------------------------------ event normalization --

def events_from_ring():
    """The telemetry ring as normalized events:
    ``(ph, name, ts_us, dur_us, args, pid)``. ``ph`` is "X"/"i"/"C"
    ("F" flows carry no time mass and are dropped)."""
    out = []
    for rec in core.records():
        ph, name, _cat, ts, val, _tid, args = rec
        if ph == "X":
            out.append(("X", name, ts, val, args, 0))
        elif ph == "i":
            out.append(("i", name, ts, 0, args, 0))
        elif ph == "C":
            out.append(("C", name, ts, 0,
                        {"value": val, "delta": args.get("delta")}, 0))
    return out


def events_from_trace(trace):
    """A chrome trace JSON object (rank-local or merged) as normalized
    events. Counter events keep their sampled value under
    ``args["value"]`` regardless of the chrome arg key."""
    out = []
    for ev in trace.get("traceEvents", []):
        ph = ev.get("ph")
        name = ev.get("name", "")
        args = ev.get("args") or {}
        pid = ev.get("pid", 0)
        ts = ev.get("ts", 0)
        if ph == "X":
            out.append(("X", name, ts, ev.get("dur", 0), args, pid))
        elif ph in ("i", "I"):
            out.append(("i", name, ts, 0, args, pid))
        elif ph == "C" and args:
            out.append(("C", name, ts, 0,
                        {"value": next(iter(args.values()))}, pid))
    out.sort(key=lambda e: e[2])
    return out


# -------------------------------------------------- interval algebra --

def _merge(iv):
    """Merge a list of (t0, t1) intervals into a disjoint sorted
    union."""
    iv = sorted((a, b) for a, b in iv if b > a)
    out = []
    for a, b in iv:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _length(merged):
    return sum(b - a for a, b in merged)


def _subtract(merged, covered):
    """``merged`` minus ``covered`` (both disjoint sorted) as a new
    disjoint sorted list — two-pointer, O(n+m)."""
    out = []
    j = 0
    for a, b in merged:
        cur = a
        while j < len(covered) and covered[j][1] <= cur:
            j += 1
        k = j
        while k < len(covered) and covered[k][0] < b:
            ca, cb = covered[k]
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cb >= b:
                break
            k += 1
        if cur < b:
            out.append((cur, b))
    return out


def _clip(iv, t0, t1):
    return [(max(a, t0), min(b, t1)) for a, b in iv
            if min(b, t1) > max(a, t0)]


# ------------------------------------------------------- the ledger --

def _collect_intervals(events):
    """Per-category raw interval lists (µs) + the scalar observations
    the post-passes need. The marker-containment pass classifies step
    spans: a ``chaos.step_skipped`` instant inside a step span turns it
    into guard_skipped; a ``mem.oom`` instant turns it into
    oom_relower; everything else is committed work."""
    iv = {name: [] for name in _PRIORITY}
    step_spans = []         # (t0, t1)
    skip_ts, oom_ts = [], []
    preempts, resumes, requeues = [], [], []
    prefills = []           # (t0, t1) serving.prefill extents
    finish_tokens = 0
    spec_ratio = None
    brownout_edges = []     # (ts, rung)
    for ph, name, ts, dur, args, _pid in events:
        if ph == "X":
            t1 = ts + dur
            if name in _STEP_SPANS:
                step_spans.append((ts, t1, name))
            elif name in _GOODPUT_SPANS:
                iv["goodput"].append((ts, t1))
                if name == "serving.prefill":
                    prefills.append((ts, t1))
            if name in _SERVING_DISPATCH:
                iv.setdefault("_dispatch", []).append((ts, t1))
            if name in _STALL_SPANS:
                iv["data_stall"].append((ts, t1))
            elif name.startswith("checkpoint."):
                iv["checkpoint"].append((ts, t1))
        elif ph == "i":
            if name == "chaos.step_skipped":
                skip_ts.append(ts)
            elif name == "mem.oom":
                oom_ts.append(ts)
            elif name in ("recompile.trace",
                          "recompile.backend_compile"):
                dur_us = int(float(args.get("duration_s") or 0) * 1e6)
                if dur_us > 0:
                    iv["recompile"].append((ts - dur_us, ts))
            elif name == "elastic.recovered":
                ms = float(args.get("ms") or 0)
                if ms > 0:
                    iv["elastic_recovery"].append(
                        (ts - int(ms * 1e3), ts))
            elif name == "serving.preempt":
                preempts.append(ts)
            elif name == "serving.resumed":
                resumes.append(ts)
            elif name == "serving.requeued":
                requeues.append(ts)
            elif name == "serving.brownout":
                try:
                    brownout_edges.append((ts, int(args.get("rung",
                                                           0))))
                except (TypeError, ValueError):
                    pass
            elif name in ("serving.finish", "serving.evict"):
                try:
                    finish_tokens += int(args.get("emitted") or 0)
                except (TypeError, ValueError):
                    pass
        elif ph == "C" and name == "serving.spec_draft_ratio":
            try:
                spec_ratio = float(args.get("value"))
            except (TypeError, ValueError):
                pass

    # marker containment: route each step span by the markers inside
    # it. Gluon records trainer.step AND a nested update span — both
    # route time, but only one kind counts steps (trainer.step when
    # present; bare update spans only for Module-style workloads).
    skip_ts.sort()
    oom_ts.sort()
    committed = skipped = oomed = 0
    count_name = ("trainer.step"
                  if any(n == "trainer.step" for _a, _b, n in step_spans)
                  else "update")
    for t0, t1, name in step_spans:
        if _any_in(skip_ts, t0, t1):
            iv["guard_skipped"].append((t0, t1))
            skipped += name == count_name
        elif _any_in(oom_ts, t0, t1):
            iv["oom_relower"].append((t0, t1))
            oomed += name == count_name
        else:
            iv["goodput"].append((t0, t1))
            committed += name == count_name

    # FIFO pairing: the k-th preempt resolves at the first resume after
    # it (the batcher re-admits parked work oldest-first); an unpaired
    # preempt stalls to the end of the window (clipped later).
    resumes.sort()
    ri = 0
    for pts in sorted(preempts):
        while ri < len(resumes) and resumes[ri] <= pts:
            ri += 1
        end = resumes[ri] if ri < len(resumes) else None
        if ri < len(resumes):
            ri += 1
        iv["preempt_stall"].append((pts, end if end is not None
                                    else float("inf")))
    # a requeued request pays its re-prefill again: charge the first
    # prefill span starting at/after each requeue instant
    prefills.sort()
    pi = 0
    for rts in sorted(requeues):
        while pi < len(prefills) and prefills[pi][0] < rts:
            pi += 1
        if pi < len(prefills):
            iv["requeue_redone"].append(prefills[pi])
            pi += 1
    # brownout: intervals where the rung is above 0
    open_ts = None
    for ts, rung in sorted(brownout_edges):
        if rung > 0 and open_ts is None:
            open_ts = ts
        elif rung == 0 and open_ts is not None:
            iv["brownout"].append((open_ts, ts))
            open_ts = None
    if open_ts is not None:
        iv["brownout"].append((open_ts, float("inf")))

    return iv, {"committed": committed, "skipped": skipped,
                "oom": oomed, "tokens": finish_tokens,
                "spec_ratio": spec_ratio}


def _any_in(sorted_ts, t0, t1):
    import bisect
    i = bisect.bisect_left(sorted_ts, t0)
    return i < len(sorted_ts) and sorted_ts[i] <= t1


def compute_ledger(events=None, wall_us=None):
    """Classify the observed wall window. ``events`` defaults to the
    live ring; ``wall_us`` overrides the window length (default: first
    record to last record end). Returns the ledger dict; the invariant
    ``goodput_ms + sum(badput_ms) + untracked_ms == wall_ms`` holds to
    float precision."""
    if events is None:
        events = events_from_ring()
    iv, obs = _collect_intervals(events)
    spans = [(ts, ts + dur) for ph, _n, ts, dur, _a, _p in events
             if ph == "X"] + \
        [(ts, ts) for ph, _n, ts, _d, _a, _p in events if ph != "X"]
    # recompile/recovery intervals reconstructed backwards from their
    # end instant may begin before the first record — they extend the
    # observed window (that compile time was real wall time)
    for cat in ("recompile", "elastic_recovery"):
        spans.extend((a, b) for a, b in iv[cat])
    if not spans:
        return _empty_ledger()
    t0 = min(a for a, _b in spans)
    t1 = max(b for _a, b in spans if b != float("inf"))
    if wall_us is not None:
        t1 = t0 + int(wall_us)
    if t1 <= t0:
        return _empty_ledger()

    covered = []
    assigned = {}
    for cat in _PRIORITY:
        merged = _merge(_clip(iv[cat], t0, t1))
        assigned[cat] = _length(_subtract(merged, covered)) / 1e3
        covered = _merge(covered + merged)

    wall_ms = (t1 - t0) / 1e3
    goodput_ms = assigned.pop("goodput")
    badput = {cat: assigned.get(cat, 0.0) for cat in CATEGORIES}

    # spec_rejected post-pass: a scalar transfer out of goodput — the
    # dispatch share of goodput times the rejected-draft fraction
    # (1 - the serving.spec_draft_ratio gauge's last sample)
    ratio = obs["spec_ratio"]
    if ratio is not None and ratio < 1.0 and goodput_ms > 0:
        disp = _length(_merge(_clip(iv.get("_dispatch", []),
                                    t0, t1))) / 1e3
        spec_ms = min(goodput_ms, disp * max(0.0, 1.0 - ratio))
        badput["spec_rejected"] = spec_ms
        goodput_ms -= spec_ms

    badput_total = sum(badput.values())
    untracked = max(wall_ms - goodput_ms - badput_total, 0.0)
    return {
        "wall_ms": wall_ms,
        "goodput_ms": goodput_ms,
        "goodput_fraction": goodput_ms / wall_ms if wall_ms else 0.0,
        "badput_ms": badput,
        "badput_total_ms": badput_total,
        "untracked_ms": untracked,
        "untracked_fraction": untracked / wall_ms if wall_ms else 0.0,
        "steps": {"committed": obs["committed"],
                  "skipped": obs["skipped"], "oom": obs["oom"]},
        "tokens_emitted": obs["tokens"],
        "window_us": [int(t0), int(t1)],
    }


def _empty_ledger():
    return {"wall_ms": 0.0, "goodput_ms": 0.0, "goodput_fraction": 0.0,
            "badput_ms": {cat: 0.0 for cat in CATEGORIES},
            "badput_total_ms": 0.0, "untracked_ms": 0.0,
            "untracked_fraction": 0.0,
            "steps": {"committed": 0, "skipped": 0, "oom": 0},
            "tokens_emitted": 0, "window_us": [0, 0]}


# -------------------------------------------------- critical path ----

_PHASES = ("forward", "backward", "allreduce", "update")


def critical_path(events):
    """Walk the per-rank step lattice of a merged (or rank-local)
    trace. For step i: the window runs from the earliest rank's phase
    start to the latest rank's ``trainer.step`` end; the rank ending
    last is the critical rank; its phase durations + the skew it
    started late by bound the step. Returns None when no
    ``trainer.step`` spans exist (serving-only trace)."""
    by_rank = {}
    for ph, name, ts, dur, _args, pid in events:
        if ph != "X":
            continue
        if name == "trainer.step" or name in _PHASES:
            by_rank.setdefault(pid, {}).setdefault(name, []).append(
                (ts, ts + dur))
    ranks = sorted(r for r, sp in by_rank.items()
                   if sp.get("trainer.step"))
    if not ranks:
        return None
    for sp in by_rank.values():
        for lst in sp.values():
            lst.sort()
    nsteps = max(len(by_rank[r]["trainer.step"]) for r in ranks)

    bound = {}              # (rank, phase) -> us
    skew_us = other_us = total_us = 0
    counted = 0
    for i in range(nsteps):
        parts = []
        for r in ranks:
            steps = by_rank[r]["trainer.step"]
            if i >= len(steps):
                continue
            s0, s1 = steps[i]
            w0 = s0
            for phs in _PHASES:
                lst = by_rank[r].get(phs, [])
                if i < len(lst):
                    w0 = min(w0, lst[i][0])
            parts.append((r, w0, s1))
        if not parts:
            continue
        counted += 1
        step_start = min(w0 for _r, w0, _s1 in parts)
        crit_rank, crit_w0, crit_end = max(parts, key=lambda p: p[2])
        step_wall = crit_end - step_start
        total_us += step_wall
        skew = max(crit_w0 - step_start, 0)
        skew_us += skew
        phase_sum = 0
        for phs in _PHASES:
            lst = by_rank[crit_rank].get(phs, [])
            if i < len(lst):
                d = lst[i][1] - lst[i][0]
                # phases nest (allreduce/update inside trainer.step);
                # forward/backward precede it — all charge the critical
                # rank, clamped so a step never over-attributes
                d = min(d, step_wall - skew - phase_sum)
                if d > 0:
                    bound[(crit_rank, phs)] = \
                        bound.get((crit_rank, phs), 0) + d
                    phase_sum += d
        other_us += max(step_wall - skew - phase_sum, 0)

    if not total_us:
        return None
    rows = [{"rank": r, "phase": p, "ms": us / 1e3,
             "fraction": us / total_us}
            for (r, p), us in bound.items()]
    rows.sort(key=lambda x: -x["ms"])
    return {"steps": counted, "ranks": ranks, "bound": rows,
            "skew_ms": skew_us / 1e3,
            "skew_fraction": skew_us / total_us,
            "other_ms": other_us / 1e3,
            "other_fraction": other_us / total_us,
            "total_ms": total_us / 1e3}


# ------------------------------------------------------- rendering ---

def format_table(ledger, cpath=None):
    """The ledger (+ optional critical path) as aggregate-table-style
    lines."""
    lines = ["", "Goodput ledger (wall %.1f ms; goodput + badput + "
             "untracked = wall)" % ledger["wall_ms"]]
    fmt = "  %-18s %12.1f ms %7.1f%%"
    wall = ledger["wall_ms"] or 1.0
    steps = ledger["steps"]
    extra = "   (%d steps committed" % steps["committed"]
    if ledger["tokens_emitted"]:
        extra += ", %d tokens emitted" % ledger["tokens_emitted"]
    extra += ")"
    lines.append(fmt % ("goodput", ledger["goodput_ms"],
                        100.0 * ledger["goodput_ms"] / wall) + extra)
    for cat in CATEGORIES:
        ms = ledger["badput_ms"][cat]
        if ms <= 0:
            continue
        note = ""
        if cat == "guard_skipped" and steps["skipped"]:
            note = "   (%d steps skipped)" % steps["skipped"]
        elif cat == "oom_relower" and steps["oom"]:
            note = "   (%d OOM-failed steps)" % steps["oom"]
        lines.append(fmt % (cat, ms, 100.0 * ms / wall) + note)
    warn = ""
    if ledger["untracked_fraction"] > warn_fraction():
        warn = ("   <-- above the %.0f%% budget; the ledger is "
                "missing a category" % (100.0 * warn_fraction()))
    lines.append(fmt % ("untracked", ledger["untracked_ms"],
                        100.0 * ledger["untracked_fraction"]) + warn)
    if cpath:
        lines.append("")
        lines.append("Critical path (%d rank%s, %d steps; what bounds "
                     "the step)" % (len(cpath["ranks"]),
                                    "s" if len(cpath["ranks"]) != 1
                                    else "", cpath["steps"]))
        for row in cpath["bound"][:8]:
            lines.append("  rank %-3d %-12s %12.1f ms %7.1f%%"
                         % (row["rank"], row["phase"], row["ms"],
                            100.0 * row["fraction"]))
        if cpath["skew_ms"] > 0:
            lines.append("  %-21s %12.1f ms %7.1f%%"
                         % ("straggler skew", cpath["skew_ms"],
                            100.0 * cpath["skew_fraction"]))
        if cpath["other_ms"] > 0:
            lines.append("  %-21s %12.1f ms %7.1f%%"
                         % ("other (host)", cpath["other_ms"],
                            100.0 * cpath["other_fraction"]))
    return lines


def format_table_section():
    """The aggregate-table hook (export.aggregate_table): the live
    ring's ledger + critical path, or [] when off/empty."""
    if not enabled():
        return []
    try:
        events = events_from_ring()
        ledger = compute_ledger(events)
        if not ledger["wall_ms"]:
            return []
        return format_table(ledger, critical_path(events))
    except Exception:   # noqa: BLE001 — a broken table must not break dumps
        return []


def prometheus_lines():
    """Fresh mxnet_obs_goodput_* series for the Prometheus exporter
    (rendered per scrape like everything else — no ring mutation)."""
    if not enabled():
        return []
    try:
        ledger = compute_ledger()
    except Exception:   # noqa: BLE001
        return []
    if not ledger["wall_ms"]:
        return []
    lines = [
        "# HELP mxnet_obs_goodput_fraction fraction of wall-clock "
        "spent on committed steps / kept tokens",
        "# TYPE mxnet_obs_goodput_fraction gauge",
        "mxnet_obs_goodput_fraction %.6f" % ledger["goodput_fraction"],
        "# HELP mxnet_obs_badput_ms wall-clock lost per badput "
        "category (goodput ledger taxonomy)",
        "# TYPE mxnet_obs_badput_ms gauge"]
    for cat in CATEGORIES:
        lines.append('mxnet_obs_badput_ms{category="%s"} %.3f'
                     % (cat, ledger["badput_ms"][cat]))
    lines.append('mxnet_obs_badput_ms{category="untracked"} %.3f'
                 % ledger["untracked_ms"])
    lines.append("# HELP mxnet_obs_goodput_wall_ms observed ledger "
                 "window")
    lines.append("# TYPE mxnet_obs_goodput_wall_ms gauge")
    lines.append("mxnet_obs_goodput_wall_ms %.3f" % ledger["wall_ms"])
    return lines


def healthz_snapshot():
    """The /healthz ``goodput`` section (also rides PR 17 incident
    bundles): the compact ledger for dashboards and the router."""
    if not enabled():
        return {}
    try:
        ledger = compute_ledger()
    except Exception:   # noqa: BLE001 — health must never 500
        return {}
    return {"wall_ms": round(ledger["wall_ms"], 3),
            "goodput_fraction": round(ledger["goodput_fraction"], 4),
            "goodput_ms": round(ledger["goodput_ms"], 3),
            "badput_ms": {k: round(v, 3)
                          for k, v in ledger["badput_ms"].items()
                          if v > 0},
            "untracked_fraction": round(ledger["untracked_fraction"],
                                        4),
            "steps": ledger["steps"],
            "tokens_emitted": ledger["tokens_emitted"]}


# ---------------------------------------------------- publish/archive --

def publish(ledger=None):
    """Land the ledger as gauges so all three PR 2 exporters carry it:
    ``goodput.fraction``, ``goodput.wall_ms``, ``badput.<cat>_ms``,
    ``goodput.untracked_ms``."""
    if not enabled():
        return None
    if ledger is None:
        ledger = compute_ledger()
    if not ledger["wall_ms"]:
        return ledger
    core.gauge("goodput.fraction").set(ledger["goodput_fraction"])
    core.gauge("goodput.wall_ms").set(ledger["wall_ms"])
    core.gauge("goodput.untracked_ms").set(ledger["untracked_ms"])
    for cat, ms in ledger["badput_ms"].items():
        if ms > 0:
            core.gauge("badput.%s_ms" % cat).set(ms)
    return ledger


def archive_run(ledger=None, run=None, dirpath=None):
    """Persist the ledger into the PR 18 profile store as scope-shaped
    records (``goodput.fraction``, ``goodput.goodput``,
    ``goodput.<category>``, ``goodput.untracked``, stats in ms except
    the fraction) so perf_timeline / obs_regression --history trend
    goodput across runs exactly like scope timings. One guarded branch
    when the store is off; never raises."""
    from . import profile_store as _ps
    try:
        if dirpath is None and not _ps.enabled():
            return 0
        if ledger is None:
            ledger = compute_ledger()
        if not ledger["wall_ms"]:
            return 0
        fid, cfg = _ps.config_fingerprint()
        run = run or _ps.run_id()
        ts = time.time()
        rows = [("goodput.fraction", ledger["goodput_fraction"]),
                ("goodput.goodput", ledger["goodput_ms"]),
                ("goodput.wall", ledger["wall_ms"]),
                ("goodput.untracked", ledger["untracked_ms"])]
        rows += [("goodput.%s" % cat, ms)
                 for cat, ms in ledger["badput_ms"].items() if ms > 0]
        wrote = 0
        for scope, val in rows:
            rec = {"schema": _ps.SCHEMA, "kind": "scope", "run": run,
                   "ts": ts, "host": _ps._host(), "scope": scope,
                   "sig": _ps.signature_key(scope, "", fid),
                   "signature": "", "fingerprint": fid, "config": cfg,
                   "stats": {"count": 1, "total_ms": float(val),
                             "p50_ms": float(val),
                             "p99_ms": float(val)},
                   "flops": 0, "hbm_bytes": 0}
            if _ps.append(rec, dirpath=dirpath) is not None:
                wrote += 1
        if wrote:
            _ps.prune(dirpath=dirpath)
        return wrote
    except Exception:   # noqa: BLE001 — archiving must not break dumps
        return 0


def on_dump():
    """profiler.dump()'s goodput hook: publish the gauges (they ride
    the trace + textfile being written) and archive the run. One
    guarded branch when the ledger is off."""
    if not enabled():
        return None
    try:
        ledger = publish()
    except Exception:   # noqa: BLE001
        return None
    if ledger and ledger["wall_ms"]:
        archive_run(ledger)
    return ledger


# ------------------------------------ cross-generation stitching -----

_commit_state = {"generation": None}


def reset():
    """Forget the per-generation first-commit latch (tests)."""
    _commit_state["generation"] = None


def note_step_commit(step=None):
    """Per-committed-step hook (Trainer.step / Module.update, inside
    their existing ``if obs.enabled():`` block). Counts the commit
    and, once per elastic generation, writes the
    ``goodput.firstcommit.g<g>.rank<r>.json`` sideband record that
    closes that generation's recovery interval — the other half of
    ``elastic_downtime``'s stitching. One guarded branch when the
    ledger (or elastic) is off; never raises."""
    if not enabled():
        return
    core.counter("goodput.steps_committed").add(1)
    try:
        from ..parallel import elastic as _elastic
        if not _elastic.enabled():
            return
        g = _elastic.generation_env()
        if _commit_state["generation"] == g:
            return
        _commit_state["generation"] = g
        d = _elastic.elastic_dir()
        path = first_commit_path(d, g, _elastic.rank_env())
        if not os.path.exists(path):
            _elastic._atomic_write_json(
                path, {"generation": int(g),
                       "rank": int(_elastic.rank_env()),
                       "step": None if step is None else int(step),
                       "wall": time.time()})
    except Exception:   # noqa: BLE001 — sideband writes never take a step down
        pass


def first_commit_path(d, generation, rank):
    return os.path.join(d, "goodput.firstcommit.g%d.rank%d.json"
                        % (int(generation), int(rank)))


def read_first_commit(d, generation):
    """The earliest first-commit record of ``generation`` across
    ranks, or None."""
    best = None
    try:
        names = os.listdir(d)
    except OSError:
        return None
    prefix = "goodput.firstcommit.g%d.rank" % int(generation)
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if best is None or rec.get("wall", 0) < best.get("wall", 0):
            best = rec
    return best


def elastic_downtime(d):
    """Stitch the elastic sideband into per-generation recovery
    intervals: for every ``shrink.g<g>.json``, downtime runs from the
    shrink's wall stamp (failure detected, generation g-1 still dying)
    to generation g's first committed step (``note_step_commit``
    record; fallbacks: the g ``gen.json`` commit, then g's earliest
    heartbeat) — an interval that SPANS the generation boundary by
    construction. Returns a wall-ordered list of
    ``{"generation", "from_wall", "to_wall", "ms", "dead",
    "survivors", "closed_by"}``."""
    out = []
    if not d:
        return out
    try:
        names = os.listdir(d)
    except OSError:
        return out
    shrink_re = re.compile(r"^shrink\.g(\d+)\.json$")
    from ..parallel import elastic as _elastic
    for name in names:
        m = shrink_re.match(name)
        if not m:
            continue
        g = int(m.group(1))
        rec = _elastic.read_shrink_record(d, g)
        if not rec:
            continue
        start = float(rec.get("wall", 0.0))
        end, closed_by = None, None
        fc = read_first_commit(d, g)
        if fc and fc.get("wall"):
            end, closed_by = float(fc["wall"]), "first_commit"
        if end is None:
            gen = _elastic.read_generation(d)
            if gen and gen.get("generation") == g and gen.get("wall"):
                end, closed_by = float(gen["wall"]), "generation"
        if end is None:
            beats = _elastic.read_heartbeats(d, g)
            walls = [b.get("wall") for b in beats.values()
                     if b.get("wall")]
            if walls:
                end, closed_by = float(min(walls)), "heartbeat"
        out.append({"generation": g, "from_wall": start,
                    "to_wall": end,
                    "ms": max((end - start) * 1e3, 0.0)
                    if end is not None and start else None,
                    "dead": rec.get("dead", []),
                    "survivors": rec.get("survivors", []),
                    "closed_by": closed_by})
    out.sort(key=lambda r: r["from_wall"])
    return out
