"""SLO accounting — per-target violation counters and a rolling
attainment gauge over the serving latency histograms.

The ROADMAP-1 router needs ONE admit/shed signal per replica: "is this
replica meeting its latency objectives right now?". This module turns
the ``MXNET_OBS_SLO`` spec into that signal:

    MXNET_OBS_SLO="ttft_ms=500,itl_ms=50"        # comma or ';' joined
    MXNET_OBS_SLO="ttft_ms=500;e2e_ms=2000;queue_ms=100"

Each ``<metric>=<threshold>`` names one of the serving latency metrics
(``ttft_ms``, ``itl_ms``, ``e2e_ms``, ``queue_ms`` — the keys match the
``serving.<metric>`` histograms, but any metric a call site checks is
accepted). Every observation the serving layer records is also checked
here (``check``): a value past its threshold increments the
``serving.slo_violation.<metric>`` counter. When a request finishes,
the batcher reports whether ANY of its observations violated
(``request_complete``), and the rolling fraction of compliant requests
over the last ``MXNET_OBS_SLO_WINDOW`` completions (default 256) is
published as the ``serving.slo_attainment`` gauge — 1.0 when every
recent request met every target, degrading toward 0.0 as violations
accumulate. That gauge rides every exporter (Prometheus text/scrape,
chrome trace, aggregate table, ``/healthz``), so a router polling
``MXNET_OBS_HTTP`` gets the shed signal without parsing distributions.

A malformed spec warns ONCE and disables accounting rather than
breaking the serving path; ``parse_spec`` itself raises so tests and
tools can validate eagerly. With ``MXNET_OBS_SLO`` unset everything
here reduces to one guarded check.
"""

import threading
import warnings
from collections import deque

from . import core
from .. import _fastenv

__all__ = ["parse_spec", "targets", "active", "window", "check",
           "request_complete", "attainment", "reset",
           "DEFAULT_WINDOW"]

DEFAULT_WINDOW = 256

_lock = threading.Lock()
_spec_cache = None          # spec string the cached _targets parse from
_targets = {}
_warned = False
_results = deque()          # rolling per-request compliance booleans


def parse_spec(spec):
    """``metric=threshold`` pairs joined by ``,`` or ``;`` -> dict.
    Thresholds are positive floats; raises ValueError on anything
    malformed (the eager/validating entry point)."""
    out = {}
    for chunk in (spec or "").replace(";", ",").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        if "=" not in chunk:
            raise ValueError(
                "SLO rule %r: expected <metric>=<threshold>" % chunk)
        key, val = chunk.split("=", 1)
        key = key.strip()
        try:
            thr = float(val)
        except ValueError:
            raise ValueError("SLO rule %r: threshold %r is not a "
                             "number" % (chunk, val))
        if not key or thr <= 0:
            raise ValueError("SLO rule %r: need a metric name and a "
                             "positive threshold" % chunk)
        out[key] = thr
    return out


def targets():
    """The parsed MXNET_OBS_SLO targets (cached on the spec string so a
    monkeypatched env re-parses). A malformed spec warns once and
    yields no targets — telemetry must never break serving."""
    global _spec_cache, _targets, _warned
    spec = _fastenv.get("MXNET_OBS_SLO") or ""
    if spec != _spec_cache:
        try:
            _targets = parse_spec(spec)
        except ValueError as exc:
            if not _warned:
                warnings.warn("mxnet_tpu.observability: ignoring "
                              "malformed MXNET_OBS_SLO (%s)" % exc,
                              RuntimeWarning, stacklevel=2)
                _warned = True
            _targets = {}
        _spec_cache = spec
    return _targets


def active():
    """Any targets configured? THE call-site guard."""
    return bool(targets())


def window():
    return int(_fastenv.get("MXNET_OBS_SLO_WINDOW", DEFAULT_WINDOW))


def check(metric, value):
    """One observation against its target. Returns True (and counts a
    ``serving.slo_violation.<metric>``) when the value misses the SLO;
    False when compliant or untracked."""
    thr = targets().get(metric)
    if thr is None or value <= thr:
        return False
    core.counter("serving.slo_violation.%s" % metric).add(1)
    return True


def request_complete(compliant):
    """Fold one finished request's verdict into the rolling window and
    publish the ``serving.slo_attainment`` gauge. Returns the current
    attainment fraction."""
    w = max(window(), 1)
    with _lock:
        _results.append(bool(compliant))
        while len(_results) > w:
            _results.popleft()
        att = sum(_results) / float(len(_results))
    core.gauge("serving.slo_attainment").set(att)
    return att


def attainment():
    """Current rolling attainment (None before any completion)."""
    with _lock:
        if not _results:
            return None
        return sum(_results) / float(len(_results))


def reset():
    """Forget the rolling window and the spec cache (tests)."""
    global _spec_cache, _targets, _warned
    with _lock:
        _results.clear()
        _spec_cache = None
        _targets = {}
        _warned = False
