"""Collective hang watchdog — a post-mortem instead of a silent hang.

A synchronous collective that one rank never reaches blocks every other
rank forever, and from the outside the job just stops making progress
— the reference's WaitForVar blindness at cluster scale. The watchdog
arms before every collective dispatch (KVStore push/pull/
pushpull_fused, the cross-process all-reduce, the sharded-update
program, ring attention) and, if the operation has not completed after
``MXNET_OBS_COLLECTIVE_TIMEOUT`` seconds, dumps a post-mortem: which
collective, its bucket/dtype lane, how long it has been armed, this
rank's last completed span, and — when ``MXNET_OBS_WATCHDOG_DIR``
points at a shared directory — which ranks checked in to the same
dispatch and what each rank last finished.

Cost model: with ``MXNET_OBS`` unset or no timeout configured, a
``watch`` is one slotted object whose ``__enter__`` takes a single
guarded branch (the same budget as a disabled ``core.span``). Armed, it
is one lock + dict insert per collective; the monitor thread wakes a
few times per second only while operations are in flight. The sideband
check-in (two small file writes per collective) happens only when the
directory knob is set.

Escalation policy (``MXNET_OBS_WATCHDOG_ACTION``): by default
(``report``) the watchdog never kills the process — training may still
complete if the missing rank eventually arrives (the post-mortem then
gets a "completed after post-mortem" follow-up), and on a real hang the
operator gets the report while attaching a debugger. Under a
supervisor (k8s restart policy, a relaunch loop) hanging forever is
the WORSE outcome, so two escalations exist: ``abort`` exits with
``ABORT_EXIT_CODE`` right after the post-mortem, and ``checkpoint``
first runs the registered emergency hook
(``models/checkpoint.install_emergency_checkpoint`` wires
``save_emergency_checkpoint``) so the restart resumes from the hang
point instead of the last routine save — then aborts. Escalation fires
at most once per process; the post-mortem is always dumped first.
"""

import json
import os
import sys
import threading
import time
import warnings

from . import core
from .. import _fastenv

__all__ = ["timeout_s", "enabled", "sideband_dir", "CollectiveWatchdog",
           "get_watchdog", "watch", "read_sideband", "action",
           "set_emergency_hook", "ABORT_EXIT_CODE"]

DEFAULT_POLL_S = 0.25

# distinctive, supervisor-visible exit for watchdog-driven aborts
ABORT_EXIT_CODE = 43

_ACTIONS = ("report", "checkpoint", "abort")

_emergency_hook = None


def action():
    """MXNET_OBS_WATCHDOG_ACTION: report (default) | checkpoint |
    abort. Unknown values degrade to report."""
    a = (_fastenv.get("MXNET_OBS_WATCHDOG_ACTION") or "report").lower()
    return a if a in _ACTIONS else "report"


def set_emergency_hook(fn):
    """Register ``fn(reason)`` to run before a ``checkpoint``-action
    abort (normally ``models.checkpoint.save_emergency_checkpoint``).
    Pass None to clear."""
    global _emergency_hook
    _emergency_hook = fn


def timeout_s():
    """MXNET_OBS_COLLECTIVE_TIMEOUT in seconds; 0 (default) disarms."""
    try:
        return float(_fastenv.get("MXNET_OBS_COLLECTIVE_TIMEOUT", "0")
                     or 0.0)
    except (TypeError, ValueError):
        return 0.0


def enabled():
    """THE site guard: telemetry on AND a timeout configured (checked
    in that order — core.enabled() is the cheap common-case False)."""
    return core.enabled() and timeout_s() > 0


def sideband_dir():
    """Shared directory for cross-rank check-in files (optional) —
    ``MXNET_OBS_WATCHDOG_DIR``, or ``<MXNET_OBS_SIDEBAND_DIR>/watchdog``
    under the unified sideband root (observability.sideband)."""
    from . import sideband as _sb
    return _sb.resolve("watchdog")


def _rank():
    from . import dist
    return dist.process_index()


def _nprocs():
    from . import dist
    return dist.process_count()


class CollectiveWatchdog(object):
    """Deadline monitor for in-flight collectives.

    ``clock`` and ``timeout`` are injectable so tests drive expiry with
    fake clocks; ``thread=False`` disables the background monitor (call
    ``check()`` manually). The module singleton uses real time and a
    daemon thread."""

    def __init__(self, timeout=None, clock=time.monotonic, rank=None,
                 nprocs=None, thread=True, emit=None, action=None,
                 abort=None, emergency_hook=None):
        self._timeout = timeout
        self.clock = clock
        self._rank = rank
        self._nprocs = nprocs
        self._use_thread = thread
        self._emit = emit
        self._action = action        # None -> env knob; tests inject
        self._abort = abort          # None -> os._exit(ABORT_EXIT_CODE)
        self._emergency_hook = emergency_hook   # None -> module hook
        self._escalated = False
        self._cv = threading.Condition()
        self._active = {}            # token -> op dict
        self._seq = 0
        self._thread = None
        self.last_completed = None   # (name, info, wall_s, mono_s)
        self.reports = []            # post-mortem texts (newest last)

    # ------------------------------------------------------ identity --
    @property
    def timeout(self):
        return timeout_s() if self._timeout is None else float(self._timeout)

    @property
    def escalation(self):
        return action() if self._action is None else self._action

    @property
    def rank(self):
        return _rank() if self._rank is None else self._rank

    @property
    def nprocs(self):
        return _nprocs() if self._nprocs is None else self._nprocs

    # ------------------------------------------------------ arm/disarm --
    def arm(self, name, info=None):
        now = self.clock()
        with self._cv:
            self._seq += 1
            token = self._seq
            self._active[token] = {
                "token": token, "name": name, "info": dict(info or {}),
                "t0": now, "deadline": now + self.timeout,
                "wall0": time.time(), "fired": False}
            self._cv.notify()
        self._write_sideband()
        if self._use_thread:
            self._ensure_thread()
        return token

    def disarm(self, token):
        with self._cv:
            op = self._active.pop(token, None)
        if op is None:
            return
        self.last_completed = (op["name"], op["info"], time.time(),
                               self.clock())
        if op["fired"]:
            dur = self.clock() - op["t0"]
            self._report("[watchdog] rank %d: collective %s completed "
                         "after post-mortem (%.1fs total)"
                         % (self.rank, op["name"], dur))
        self._write_sideband()

    # -------------------------------------------------------- checking --
    def check(self, now=None):
        """Fire post-mortems for every expired, unreported operation.
        Returns the reports (also appended to ``self.reports``)."""
        now = self.clock() if now is None else now
        with self._cv:
            expired = [op for op in self._active.values()
                       if not op["fired"] and now >= op["deadline"]]
            for op in expired:
                op["fired"] = True
        out = []
        for op in expired:
            rep = self.post_mortem(op, now)
            out.append(rep)
            self.reports.append(rep)
            self._fire(op, rep)
        return out

    def _fire(self, op, report):
        self._report(report)
        if core.enabled():
            core.record_instant(
                "watchdog.postmortem", cat="watchdog",
                args={"collective": op["name"], "rank": self.rank,
                      "armed_s": round(self.clock() - op["t0"], 3)})
            core.counter("watchdog.postmortems").add(1)
        d = sideband_dir()
        if d:
            try:
                path = os.path.join(
                    d, "postmortem.rank%d.txt" % self.rank)
                with open(path, "a") as f:
                    f.write(report + "\n")
            except OSError:
                pass
        warnings.warn(
            "mxnet_tpu.observability: collective %s exceeded the %.1fs "
            "watchdog timeout on rank %d — post-mortem dumped"
            % (op["name"], self.timeout, self.rank),
            RuntimeWarning, stacklevel=2)
        from . import flight as _flight
        _flight.record_incident(
            "watchdog.hang", collective=op["name"],
            armed_s=round(self.clock() - op["t0"], 3),
            action=self.escalation, postmortem=report)
        self._escalate(op)

    # ------------------------------------------------------ escalation --
    def _escalate(self, op):
        """MXNET_OBS_WATCHDOG_ACTION policy, applied AFTER the
        post-mortem: ``checkpoint`` runs the emergency hook (best
        effort — the collective is hung, the step state is the last
        completed one) then aborts; ``abort`` aborts directly so a
        supervisor can restart the job instead of watching it hang.
        At most once per process."""
        act = self.escalation
        if act == "report" or self._escalated:
            return
        self._escalated = True
        if act == "checkpoint":
            hook = self._emergency_hook if self._emergency_hook \
                is not None else _emergency_hook
            if hook is None:
                self._report(
                    "[watchdog] rank %d: action=checkpoint but no "
                    "emergency hook registered (see models.checkpoint."
                    "install_emergency_checkpoint) — aborting without "
                    "a hang-point checkpoint" % self.rank)
            else:
                try:
                    path = hook("watchdog:%s" % op["name"])
                    self._report(
                        "[watchdog] rank %d: emergency checkpoint %s "
                        "committed before abort" % (self.rank, path))
                except Exception as e:     # noqa: BLE001 — last gasp
                    self._report(
                        "[watchdog] rank %d: emergency checkpoint "
                        "FAILED (%s: %s) — aborting anyway"
                        % (self.rank, type(e).__name__, e))
        self._report(
            "[watchdog] rank %d: action=%s — aborting with exit code "
            "%d for supervisor restart" % (self.rank, act,
                                           ABORT_EXIT_CODE))
        if self._abort is not None:
            self._abort(ABORT_EXIT_CODE)
        else:                              # pragma: no cover - fatal
            sys.stderr.flush()
            os._exit(ABORT_EXIT_CODE)

    def _report(self, text):
        if self._emit is not None:
            self._emit(text)
        else:
            print(text, file=sys.stderr, flush=True)

    # ------------------------------------------------------ post-mortem --
    def post_mortem(self, op, now=None):
        """The report for one hung operation."""
        now = self.clock() if now is None else now
        bar = "=" * 74
        lines = [bar,
                 "MXNET_OBS collective watchdog post-mortem",
                 "rank %d/%d | collective %s | armed %.1fs ago "
                 "(timeout %.1fs)"
                 % (self.rank, self.nprocs, op["name"],
                    now - op["t0"], self.timeout)]
        if op["info"]:
            lines.append("  dispatch: " + " ".join(
                "%s=%s" % (k, v) for k, v in sorted(op["info"].items())))
        if self.last_completed is not None:
            name, _info, _wall, mono = self.last_completed
            lines.append("  local last completed span: %s "
                         "(finished %.1fs ago)" % (name, now - mono))
        else:
            lines.append("  local last completed span: <none recorded>")
        others = [o for o in self._snapshot_active()
                  if o["token"] != op["token"]]
        if others:
            lines.append("  also in flight locally: " + ", ".join(
                "%s (%.1fs)" % (o["name"], now - o["t0"])
                for o in others))
        d = sideband_dir()
        if d:
            lines.append("  rank check-in (sideband %s):" % d)
            entries = read_sideband(d)
            seen = set()
            for e in sorted(entries, key=lambda e: e.get("rank", -1)):
                r = e.get("rank")
                seen.add(r)
                me = " (this rank)" if r == self.rank else ""
                if e.get("status") == "armed":
                    lines.append(
                        "    rank %s: ARMED %s seq=%s since %s%s"
                        % (r, e.get("collective"), e.get("seq"),
                           _fmt_wall(e.get("since_wall")), me))
                else:
                    last = e.get("last_completed") or {}
                    lines.append(
                        "    rank %s: idle — last completed %s @ %s "
                        "(NOT checked in)%s"
                        % (r, last.get("name", "<none>"),
                           _fmt_wall(last.get("wall")), me))
            for r in range(self.nprocs):
                if r not in seen:
                    lines.append("    rank %d: <no sideband entry> "
                                 "(NOT checked in)" % r)
        else:
            lines.append("  rank check-in: unavailable — set "
                         "MXNET_OBS_WATCHDOG_DIR to a shared directory "
                         "for cross-rank state")
        lines.append(bar)
        return "\n".join(lines)

    def _snapshot_active(self):
        with self._cv:
            return [dict(op) for op in self._active.values()]

    # --------------------------------------------------------- sideband --
    def _write_sideband(self):
        d = sideband_dir()
        if not d:
            return
        armed = None
        with self._cv:
            if self._active:
                armed = max(self._active.values(),
                            key=lambda op: op["token"])
        entry = {"rank": self.rank}
        if armed is not None:
            entry.update({"status": "armed",
                          "collective": armed["name"],
                          "seq": armed["token"],
                          "info": {k: str(v)
                                   for k, v in armed["info"].items()},
                          "since_wall": armed["wall0"]})
        else:
            entry["status"] = "idle"
        if self.last_completed is not None:
            name, _info, wall, _mono = self.last_completed
            entry["last_completed"] = {"name": name, "wall": wall}
        try:
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, ".wd.rank%d.tmp" % self.rank)
            with open(tmp, "w") as f:
                json.dump(entry, f)
            os.replace(tmp, os.path.join(d, "wd.rank%d.json" % self.rank))
        except OSError:                  # sideband is best-effort
            pass

    # ----------------------------------------------------------- thread --
    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(target=self._loop,
                             name="mxnet-obs-watchdog", daemon=True)
        self._thread = t
        t.start()

    def _loop(self):                     # pragma: no cover - timing
        while True:
            with self._cv:
                if not self._active:
                    self._cv.wait()
                    continue
                tmo = self.timeout
            poll = max(0.05, min(DEFAULT_POLL_S, tmo / 5 if tmo else
                                 DEFAULT_POLL_S))
            time.sleep(poll)
            try:
                self.check()
            except Exception:            # never take the process down
                pass


def _fmt_wall(wall):
    if not wall:
        return "<unknown>"
    return time.strftime("%H:%M:%S", time.localtime(wall)) \
        + ".%03d" % (int(wall * 1000) % 1000)


def read_sideband(d):
    """Parse every rank's check-in file under the sideband dir."""
    out = []
    for path in sorted(glob_rank_files(d)):
        try:
            with open(path) as f:
                out.append(json.load(f))
        except (OSError, ValueError):
            continue
    return out


def glob_rank_files(d):
    import glob
    return glob.glob(os.path.join(d, "wd.rank*.json"))


_WD = None
_wd_lock = threading.Lock()


def get_watchdog():
    """The process singleton (real clock + monitor thread)."""
    global _WD
    if _WD is None:
        with _wd_lock:
            if _WD is None:
                _WD = CollectiveWatchdog()
    return _WD


class watch(object):
    """``with watch("kvstore.pushpull_fused", bucket=0, lane="f32"):``
    — arms the watchdog around one collective dispatch; a single
    guarded branch when the watchdog is off (core.span's cost model).
    Also usable via explicit start()/stop()."""

    __slots__ = ("name", "info", "_token")

    def __init__(self, name, **info):
        self.name = name
        self.info = info
        self._token = None

    def start(self):
        if enabled():
            self._token = get_watchdog().arm(self.name, self.info)
        return self

    def stop(self):
        if self._token is not None:
            get_watchdog().disarm(self._token)
            self._token = None

    __enter__ = start

    def __exit__(self, *exc):
        self.stop()
