"""Distributed observability — rank identity, cross-rank trace merging,
and step-phase straggler detection.

The PR 2 telemetry core is deliberately per-process: one ring, one
counter registry, no notion of a peer. That mirrors the reference
engine's blindness — a rank blocked in Engine::WaitForVar or inside a
ps-lite push looks identical to one doing useful work
(include/mxnet/engine.h, SURVEY layer 2). Once training is multi-host
the dominant failure modes are exactly the ones a per-process view
cannot show (TF system paper, PAPERS.md): stragglers and silently hung
collectives. This module adds the cross-rank half:

* **rank identity** — every exported event carries the jax
  ``process_index`` as its chrome-trace ``pid``, so each rank is one
  lane; rank-local dumps are rank-suffixed (``trace.rank1.json``)
  instead of N processes clobbering one file.
* **clock alignment** — host ``perf_counter`` epochs differ per
  process, so rank-local timestamps share no timebase.
  ``record_clock_anchor`` runs a barrier handshake (a tiny collective,
  taken at kvstore creation) and records the local mono/wall time at
  barrier exit; all ranks exit a synchronous collective within its
  completion skew, so the anchor instants are simultaneous to within
  the collective's latency — good enough to line up millisecond-scale
  step phases. ``merge_traces`` subtracts per-rank anchor offsets and
  emits ONE chrome://tracing file with per-rank lanes.
* **straggler detection** — every ``MXNET_OBS_SKEW_EVERY`` steps the
  Trainer/Module hook all-gathers each rank's mean per-phase durations
  (forward/backward/allreduce/update) and warns when one rank exceeds
  the across-rank median by ``MXNET_OBS_STRAGGLER_FACTOR``. The last
  window's skew table is appended to ``profiler.dumps(aggregate=True)``
  as min/median/max-rank columns.

Everything here is either off the hot path (merge, exchange) or behind
the same ``core.enabled()`` gate as the rest of the telemetry.
"""

import glob
import json
import os
import time
import warnings

import numpy as np

from . import core
from .. import _fastenv

__all__ = ["PHASES", "process_index", "process_count", "rank_trace_path",
           "record_clock_anchor", "ensure_clock_anchor", "clock_anchor",
           "find_rank_traces", "merge_traces", "skew_every",
           "straggler_factor", "collect_phase_ms", "detect_stragglers",
           "exchange_phase_stats", "skew_summary", "format_skew_table",
           "step_boundary"]

PHASES = ("forward", "backward", "allreduce", "update")

DEFAULT_SKEW_EVERY = 32
DEFAULT_STRAGGLER_FACTOR = 2.0
# phases shorter than this never flag: at sub-ms scale the across-rank
# ratio is host-scheduler noise, not a straggler
MIN_STRAGGLER_MS = 0.25

_anchor = None          # barrier-handshake clock anchor (this rank)
_skew = None            # last cross-rank skew summary
_steps = 0              # step_boundary() count
_since_us = 0           # ring timestamp of the last exchange window end


def process_index():
    """This process's rank (0 when jax is absent/uninitialized)."""
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def process_count():
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


def skew_every():
    return int(_fastenv.get("MXNET_OBS_SKEW_EVERY", DEFAULT_SKEW_EVERY))


def straggler_factor():
    return float(_fastenv.get("MXNET_OBS_STRAGGLER_FACTOR",
                              DEFAULT_STRAGGLER_FACTOR))


# ------------------------------------------------------ rank-local IO --

def rank_trace_path(path, rank=None):
    """Rank-suffixed dump target: rank 0 keeps the bare name, rank r
    writes ``<stem>.rank<r><ext>`` — N processes sharing one configured
    filename no longer clobber a single JSON."""
    rank = process_index() if rank is None else int(rank)
    if rank == 0:
        return path
    root, ext = os.path.splitext(path)
    return "%s.rank%d%s" % (root, rank, ext or ".json")


def find_rank_traces(base):
    """All rank-local traces for a configured filename: the bare file
    (rank 0) plus every ``<stem>.rank*<ext>`` sibling, sorted by rank."""
    root, ext = os.path.splitext(base)
    paths = [base] if os.path.exists(base) else []
    ranked = glob.glob("%s.rank*%s" % (root, ext or ".json"))

    def _rank_of(p):
        stem = os.path.splitext(p)[0]
        try:
            return int(stem.rsplit(".rank", 1)[1])
        except (IndexError, ValueError):
            return 1 << 30
    return paths + sorted(ranked, key=_rank_of)


# ------------------------------------------------------ clock anchor --

def record_clock_anchor(barrier_fn=None, rounds=4, rank=None, nprocs=None,
                        _mono_us=None, _wall_us=None):
    """Barrier-handshake clock calibration (taken at kvstore creation).

    ``barrier_fn`` runs one synchronous cross-rank collective; it is
    called ``rounds`` times (the first calls absorb compile/rendezvous
    cost) and the local clock is read immediately after the last —
    every rank reads within the final collective's completion skew, so
    the anchors mark (approximately) one global instant.
    ``_mono_us``/``_wall_us`` inject fake clocks for tests."""
    global _anchor
    if barrier_fn is not None:
        for _ in range(max(int(rounds), 1)):
            barrier_fn()
    mono = core._now_us() if _mono_us is None else int(_mono_us)
    wall = int(time.time() * 1e6) if _wall_us is None else int(_wall_us)
    _anchor = {"rank": process_index() if rank is None else int(rank),
               "nprocs": process_count() if nprocs is None else int(nprocs),
               "mono_us": mono, "wall_us": wall,
               "barrier": barrier_fn is not None}
    return _anchor


def ensure_clock_anchor():
    """Anchor for dump time: keeps any barrier-calibrated anchor, else
    records a local (offset-0) one so single-process merges work."""
    if _anchor is None:
        record_clock_anchor()
    return _anchor


def clock_anchor():
    return _anchor


# ------------------------------------------------------ trace merging --

def merge_traces(paths, out=None):
    """Combine rank-local chrome traces into one file with per-rank
    lanes on a common timebase.

    ``paths``: a list of trace files, or one configured filename whose
    rank-suffixed siblings are discovered (``find_rank_traces``). Each
    rank's events shift by its clock-anchor offset against the lowest
    anchored rank (traces without an anchor merge unshifted and are
    listed in ``otherData.unaligned_ranks``), land on ``pid = rank``,
    and get a ``process_name`` metadata row. Returns the merged trace
    dict; writes it to ``out`` when given."""
    if isinstance(paths, str):
        paths = find_rank_traces(paths)
    if not paths:
        raise ValueError("merge_traces: no input traces")
    loaded = []
    for i, p in enumerate(paths):
        with open(p) as f:
            trace = json.load(f)
        other = trace.get("otherData", {}) or {}
        rank = other.get("rank")
        if rank is None:
            rank = i
        loaded.append((int(rank), other.get("clock_anchor"), trace, p))
    loaded.sort(key=lambda t: t[0])

    ref = next((a for _, a, _, _ in loaded if a), None)
    # per-rank serving/latency histograms merge BUCKET-WISE (same
    # log-bucket edges on every rank), so the merged trace carries
    # fleet-level distributions, not one rank's
    from . import histogram as _hist
    hist_merged, hist_conflicts = _hist.merge_state_maps(
        [(t.get("otherData") or {}).get("histograms")
         for _, _, t, _ in loaded])
    events, offsets, unaligned, dropped = [], {}, [], 0
    for rank, anchor, trace, _p in loaded:
        if anchor and ref:
            off = int(anchor["mono_us"]) - int(ref["mono_us"])
        else:
            off = 0
            unaligned.append(rank)
        offsets[rank] = off
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": "rank %d" % rank}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue            # re-emitted above, per merged rank
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = ev["ts"] - off
            if ev.get("ph") in ("s", "t", "f") and "id" in ev:
                # flow chains bind on (cat, id) across the WHOLE trace,
                # not per pid — scope ids per rank so rank 0's request
                # 0 and rank 1's request 0 stay separate chains
                ev["id"] = int(ev["id"]) + (rank << 32)
            events.append(ev)
        dropped += int((trace.get("otherData") or {})
                       .get("dropped_records", 0) or 0)

    # chrome://tracing renders negative timestamps poorly: rebase the
    # merged timeline so the earliest event sits at 0
    t0 = min((ev["ts"] for ev in events if "ts" in ev), default=0)
    if t0:
        for ev in events:
            if "ts" in ev:
                ev["ts"] -= t0
    merged = {
        "traceEvents": events, "displayTimeUnit": "ms",
        "otherData": {
            "recorder": "mxnet_tpu.observability.merge_traces",
            "merged_ranks": [r for r, _, _, _ in loaded],
            "clock_offsets_us": {str(r): o for r, o in offsets.items()},
            "unaligned_ranks": unaligned,
            "histograms": hist_merged,
            "histogram_merge_conflicts": hist_conflicts,
            "dropped_records": dropped}}
    if out:
        with open(out, "w") as f:
            json.dump(merged, f)
    return merged


# ----------------------------------------------- straggler detection --

def collect_phase_ms(since_us=0, phases=PHASES):
    """Mean duration (ms) per step phase from the local ring, over
    records at/after ``since_us`` — the per-rank sample one skew
    exchange contributes."""
    sums = {p: 0.0 for p in phases}
    counts = {p: 0 for p in phases}
    for rec in core.records():
        ph, name, _cat, ts, dur, _tid, _args = rec
        if ph == "X" and name in sums and ts >= since_us:
            sums[name] += dur / 1000.0
            counts[name] += 1
    return {p: (sums[p] / counts[p] if counts[p] else 0.0)
            for p in phases}


def detect_stragglers(phase_table, factor=None, min_ms=MIN_STRAGGLER_MS):
    """Reduce per-rank phase durations to a skew summary + straggler
    verdicts.

    ``phase_table``: {phase: [per-rank ms]}. A rank straggles on a
    phase when its duration exceeds the across-rank median by
    ``factor`` (``MXNET_OBS_STRAGGLER_FACTOR``) and the duration
    clears the ``min_ms`` noise floor. The flagging median is taken
    LEAVE-ONE-OUT (the other ranks' median): at small world sizes the
    straggler's own sample drags the plain median toward itself — with
    2 ranks a 5x-slow rank would otherwise never exceed 2x "median"."""
    factor = straggler_factor() if factor is None else float(factor)
    summary = {"phases": {}, "stragglers": [], "factor": factor,
               "nprocs": 0}
    for phase, vals in phase_table.items():
        vals = [float(v) for v in vals]
        if not vals:
            continue
        summary["nprocs"] = max(summary["nprocs"], len(vals))
        mn, mx = min(vals), max(vals)
        max_rank = vals.index(mx)
        others = vals[:max_rank] + vals[max_rank + 1:]
        med = float(np.median(others)) if others else mx
        entry = {
            "ms": vals, "min_ms": mn, "min_rank": vals.index(mn),
            "median_ms": med, "max_ms": mx, "max_rank": max_rank,
            "ratio": (mx / med) if med > 0
            else (float("inf") if mx > 0 else 1.0)}
        summary["phases"][phase] = entry
        if mx >= min_ms and len(vals) > 1 and med > 0 \
                and mx > med * factor:
            summary["stragglers"].append({
                "phase": phase, "rank": max_rank, "ms": mx,
                "median_ms": med, "ratio": entry["ratio"]})
    return summary


def _allgather_vec(vec):
    """All-gather one small float32 vector across ranks -> [nprocs, d]
    host array. Collective: every rank must call in (the exchange runs
    at deterministic step counts). Single-process: identity."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    vec = np.asarray(vec, np.float32).reshape(-1)
    n = jax.process_count()
    if n <= 1:
        return vec[None]
    per_proc = tuple(
        next(d for d in jax.devices() if d.process_index == p)
        for p in range(n))
    mesh = Mesh(np.asarray(per_proc), ("worker",))
    mine = jax.device_put(jnp.asarray(vec)[None],
                          per_proc[jax.process_index()])
    garr = jax.make_array_from_single_device_arrays(
        (n, vec.shape[0]), NamedSharding(mesh, P("worker")), [mine])
    gathered = jax.jit(
        lambda x: x,
        out_shardings=NamedSharding(mesh, P()))(garr)
    return np.asarray(gathered.addressable_data(0))


def exchange_phase_stats(phase_ms=None, allgather=None, rank=None,
                         warn=True):
    """One cross-rank skew exchange: all-gather this rank's per-phase
    means, update the skew summary, publish skew gauges, and warn on
    stragglers. ``phase_ms``/``allgather``/``rank`` are injectable for
    tests (fake clocks, no real multi-host needed)."""
    global _skew, _since_us
    local = collect_phase_ms(_since_us) if phase_ms is None \
        else dict(phase_ms)
    _since_us = core._now_us()
    vec = np.asarray([local.get(p, 0.0) for p in PHASES], np.float32)
    gathered = (_allgather_vec if allgather is None else allgather)(vec)
    gathered = np.asarray(gathered, np.float32)
    table = {p: list(gathered[:, i]) for i, p in enumerate(PHASES)}
    summary = detect_stragglers(table)
    summary["rank"] = process_index() if rank is None else int(rank)
    summary["step"] = _steps
    _skew = summary
    for phase, e in summary["phases"].items():
        core.gauge("skew.%s.max_over_median" % phase).set(
            e["ratio"] if np.isfinite(e["ratio"]) else 0.0)
    try:
        from .. import storage
        storage.publish_device_memory_gauges()
    except Exception:
        pass
    if warn:
        for s in summary["stragglers"]:
            warnings.warn(
                "mxnet_tpu.observability: cross-rank straggler — rank "
                "%d %s %.2f ms vs across-rank median %.2f ms (x%.1f, "
                "factor %.1f)" % (s["rank"], s["phase"], s["ms"],
                                  s["median_ms"], s["ratio"],
                                  summary["factor"]),
                RuntimeWarning, stacklevel=2)
    return summary


def skew_summary():
    """The last exchange's cross-rank skew summary (None before one)."""
    return _skew


def format_skew_table(summary=None):
    """The skew summary as table lines — appended to
    ``profiler.dumps(aggregate=True)`` after the counter section."""
    summary = _skew if summary is None else summary
    if not summary or not summary["phases"]:
        return []
    fmt = "%-12s %14s %10s %14s %12s  %s"
    lines = ["",
             "Cross-rank step-phase skew (%d ranks, straggler factor "
             "%.1fx)" % (summary.get("nprocs", 0),
                         summary.get("factor", 0.0)),
             "=" * 28,
             fmt % ("Phase", "Min(rank)", "Med(rest)", "Max(rank)",
                    "Max/Median", "")]
    flagged = {(s["phase"], s["rank"]) for s in summary["stragglers"]}
    for phase in PHASES:
        e = summary["phases"].get(phase)
        if e is None:
            continue
        mark = "<< STRAGGLER r%d" % e["max_rank"] \
            if (phase, e["max_rank"]) in flagged else ""
        ratio = "%.2f" % e["ratio"] if np.isfinite(e["ratio"]) else "inf"
        lines.append(fmt % (
            phase, "%.3f (r%d)" % (e["min_ms"], e["min_rank"]),
            "%.3f" % e["median_ms"],
            "%.3f (r%d)" % (e["max_ms"], e["max_rank"]), ratio, mark))
    return lines


def step_boundary(kv=None):
    """Trainer/Module hook (call only when ``core.enabled()``): counts
    steps and, every ``MXNET_OBS_SKEW_EVERY`` steps of a multi-worker
    job, runs one skew exchange. Telemetry must never break training:
    exchange failures degrade to a single warning."""
    global _steps
    _steps += 1
    every = skew_every()
    if every <= 0:
        return
    n = kv.num_workers if kv is not None else process_count()
    if n <= 1 or _steps % every:
        return
    try:
        exchange_phase_stats()
    except Exception as exc:          # pragma: no cover - defensive
        warnings.warn("mxnet_tpu.observability: skew exchange failed "
                      "(%s); continuing without cross-rank stats"
                      % (exc,), RuntimeWarning, stacklevel=2)


def _reset_for_tests():
    """Clear module state (anchor, skew window, step count)."""
    global _anchor, _skew, _steps, _since_us
    _anchor = None
    _skew = None
    _steps = 0
    _since_us = 0
