"""Tensor inspection and NaN guarding — on any intermediate, eager or
compiled.

Reference: src/common/tensor_inspector.h:815 (TensorInspector:
print_string/interactive_print, check_value with NegativeChecker/
NaNChecker, dump_to_file) — a debugging tool usable on any tensor at any
point. TPU-native redesign: values inside a jit-compiled graph are not
host-addressable, so inspection rides `jax.debug.callback` — the
callback is staged into the XLA program and fires on the HOST with the
materialized device value every execution, which is precisely the
TensorInspector contract under a compiler.

Three layers:
* :func:`inspect` / :class:`TensorInspector` — explicit, user-placed
  summaries/dumps of a tensor (works on NDArray, jax arrays, and inside
  jit/hybridized graphs).
* :func:`guard_value` — attach a finite-ness check to a value.
* NaN-guard mode (``MXNET_NAN_GUARD=1`` or :func:`set_nan_guard`) —
  executors/CachedOp guard every graph-node output with its op name, so
  the first non-finite intermediate is reported at its source instead
  of surfacing as a NaN loss many layers later.

Reports go to the active sink (default: print to stderr + raise-on-bad
for guards); tests install a capturing sink via :func:`set_sink`.
"""

import os
import sys
import threading

import numpy as np

import jax
import jax.numpy as jnp

from . import _fastenv as _fe

__all__ = ["inspect", "TensorInspector", "guard_value", "set_nan_guard",
           "nan_guard_enabled", "set_sink"]

_state = threading.local()


def _sink():
    return getattr(_state, "sink", None) or _default_sink


def _default_sink(report):
    sys.stderr.write(report["text"] + "\n")
    if report.get("kind") == "guard" and report.get("bad"):
        # a guard report means a non-finite intermediate: make it loud.
        # (Raising inside a debug callback cannot abort the already-
        # running XLA computation; the error text pinpoints the op.)
        sys.stderr.write(
            "*** NaN guard: non-finite value in %s ***\n" % report["tag"])


def set_sink(fn):
    """Install a report sink (callable(report_dict)) for this thread;
    None restores the default stderr sink. Returns the previous sink."""
    prev = getattr(_state, "sink", None)
    _state.sink = fn
    return prev


# ------------------------------------------------------------- inspect --
def _is_floatish(dtype):
    # ml_dtypes floats (bfloat16, float8_*) report kind 'V' to numpy;
    # they are the DOMINANT dtypes on this stack and must not blind the
    # NaN accounting
    if dtype.kind == "f":
        return True
    try:
        import ml_dtypes
        return dtype in (np.dtype(ml_dtypes.bfloat16),)
    except ImportError:
        return False


def _summarize(tag, value, kind):
    v = np.asarray(value)
    isf = _is_floatish(v.dtype)
    if isf and v.dtype.kind != "f":
        v = v.astype(np.float32)      # widen bf16 for the statistics
    finite = np.isfinite(v.astype(np.float64)) if isf \
        else np.ones(v.shape, bool)
    n_nan = int(np.isnan(v).sum()) if isf else 0
    n_inf = int(np.isinf(v).sum()) if isf else 0
    report = {
        "kind": kind, "tag": tag, "shape": tuple(v.shape),
        "dtype": str(v.dtype), "nan": n_nan, "inf": n_inf,
        "bad": bool(n_nan or n_inf),
    }
    if v.size:
        fv = v[finite] if v.dtype.kind == "f" else v
        if fv.size:
            report.update(min=float(np.min(fv)), max=float(np.max(fv)),
                          mean=float(np.mean(fv.astype(np.float64))))
    report["text"] = (
        "[%s] %s shape=%s dtype=%s min=%s max=%s mean=%s nan=%d inf=%d"
        % (kind, tag, report["shape"], report["dtype"],
           report.get("min"), report.get("max"),
           ("%.6g" % report["mean"]) if "mean" in report else None,
           n_nan, n_inf))
    _sink()(report)


def inspect(data, tag="tensor"):
    """Print a summary (shape/dtype/min/max/mean/NaN/Inf counts) of
    `data` — NDArray, jax array, or numpy. Inside jit (or a hybridized
    block) the summary is computed on the host from the executed value
    via jax.debug.callback; the value is returned unchanged so the call
    can be inserted into a computation."""
    arr = getattr(data, "_data", data)
    if isinstance(arr, jax.core.Tracer):
        jax.debug.callback(lambda v: _summarize(tag, v, "inspect"), arr)
        return data
    _summarize(tag, np.asarray(arr), "inspect")
    return data


class TensorInspector:
    """Reference-shaped wrapper (tensor_inspector.h): construct over a
    tensor, then print_string()/check_value()/dump_to_file()."""

    def __init__(self, data, tag="tensor"):
        self._data = getattr(data, "_data", data)
        self._tag = tag

    def print_string(self):
        inspect(self._data, self._tag)
        return self

    def to_string(self):
        v = np.asarray(self._data)
        return np.array2string(v, threshold=64)

    def check_value(self, checker=None):
        """checker: callable(np.ndarray) -> bool array of violations, or
        None for the NaN/Inf checker (reference CheckerType::NaNChecker).
        Returns the number of violations (eager) or stages a host check
        (traced)."""
        if checker is None:
            checker = lambda v: ~np.isfinite(v)
        if isinstance(self._data, jax.core.Tracer):
            tag = self._tag

            def _cb(v):
                bad = int(np.asarray(checker(np.asarray(v))).sum())
                if bad:
                    _sink()({"kind": "check", "tag": tag, "bad": True,
                             "violations": bad,
                             "text": "[check] %s: %d violations"
                             % (tag, bad)})
            jax.debug.callback(_cb, self._data)
            return None
        return int(np.asarray(checker(np.asarray(self._data))).sum())

    def dump_to_file(self, path):
        """Save the value as .npy (reference dump_to_file writes a
        binary blob; .npy is the portable equivalent). Works under jit
        via a host callback."""
        if isinstance(self._data, jax.core.Tracer):
            jax.debug.callback(
                lambda v: np.save(path, np.asarray(v)), self._data)
            return self
        np.save(path, np.asarray(self._data))
        return self


# ----------------------------------------------------------- NaN guard --
_guard_flag = None


def nan_guard_enabled():
    """Hot path (every CachedOp call keys its compiled-fn cache on
    this) — reads through _fastenv, not os.environ."""
    if _guard_flag is not None:
        return _guard_flag
    return (_fe.get("MXNET_NAN_GUARD") or "0").lower() in (
        "1", "true")


def set_nan_guard(enabled):
    """Toggle NaN guarding programmatically (overrides the env var).
    Guards are staged at TRACE time: executors bound and CachedOps
    compiled while the guard is on carry the checks (CachedOp keys its
    compiled-function cache on the flag, so toggling retraces)."""
    global _guard_flag
    _guard_flag = bool(enabled)


def guard_value(x, tag):
    """Attach a host-side finite-ness check to a traced or eager float
    value; returns x. The report names `tag` (op:name), so the FIRST
    non-finite intermediate pinpoints its producer."""
    dt = getattr(x, "dtype", None)
    if dt is None or jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_:
        return x

    def _cb(v):
        v = np.asarray(v)
        n_nan = int(np.isnan(v).sum())
        n_inf = int(np.isinf(v).sum())
        if n_nan or n_inf:
            _sink()({"kind": "guard", "tag": tag, "bad": True,
                     "nan": n_nan, "inf": n_inf,
                     "text": "[guard] %s: nan=%d inf=%d shape=%s"
                     % (tag, n_nan, n_inf, tuple(v.shape))})
    jax.debug.callback(_cb, x)
    return x
