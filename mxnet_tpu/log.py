"""Colored logging helpers (reference: python/mxnet/log.py)."""

import logging
import sys

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL

PY3 = True

_COLORS = {WARNING: "\x1b[33m", INFO: "\x1b[32m", DEBUG: "\x1b[34m",
           ERROR: "\x1b[31m", CRITICAL: "\x1b[35m"}


class _Formatter(logging.Formatter):
    """Level-colored single-letter-prefix formatter (reference
    log.py _Formatter): `W0730 12:00:00 message` with ANSI colors on
    ttys."""

    def __init__(self, colored=None):
        self.colored = sys.stderr.isatty() if colored is None else colored
        super(_Formatter, self).__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        label = record.levelname[0]
        fmt = "%s%s%%(asctime)s %%(message)s%s" % (
            _COLORS.get(record.levelno, "") if self.colored else "",
            label, "\x1b[0m" if self.colored else "")
        self._style._fmt = fmt
        return super(_Formatter, self).format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger with the mxnet formatter attached once."""
    logger = logging.getLogger(name)
    if getattr(logger, "_init_done", False):
        logger.setLevel(level)
        return logger
    logger._init_done = True
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        handler.setFormatter(_Formatter(colored=False))
    else:
        handler = logging.StreamHandler()
        handler.setFormatter(_Formatter())
    logger.addHandler(handler)
    logger.setLevel(level)
    return logger


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated spelling kept for reference parity."""
    import warnings
    warnings.warn("getLogger is deprecated, use get_logger instead")
    return get_logger(name, filename, filemode, level)
